"""GPT model family — the flagship decoder-only LM, TPU-first.

Parity target: the FleetX GPT-3 pretraining recipe the reference's hybrid
parallel stack exists to serve (SURVEY.md §6 north star: GPT-3 1.3B at
>=35% MFU).  The reference implements this model with fused CUDA ops
(paddle/fluid/operators/fused/fused_multi_transformer_op.cu,
fused_attention_op.cu) driven by fleet's mpu layers
(fleet/layers/mpu/mp_layers.py:39,155,293,438).  Here the same architecture is
written once in terms of:

* mpu TP layers (VocabParallelEmbedding / ColumnParallelLinear /
  RowParallelLinear) whose parameters carry PartitionSpecs — GSPMD partitions
  the matmuls over the 'mp' mesh axis;
* `scaled_dot_product_attention`, which routes to the Pallas flash-attention
  kernel on TPU (paddle_tpu/kernels/flash_attention.py) — the analog of the
  reference's fmha_ref.h, minus the S×S materialisation;
* `jax.checkpoint`-backed `recompute` for activation checkpointing
  (fleet/utils/recompute.py:350 parity);
* sequence-axis sharding constraints so long sequences can shard over a
  'sep' mesh axis (context parallelism — a TPU extension; the reference has
  none, SURVEY.md §5.7).

Everything is global-shape SPMD: no per-rank branches, no explicit p2p.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..distributed import mesh as mesh_mod

from ..distributed.fleet.layers.mpu.mp_layers import (
    _U,
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    _constrain,
    _mp_info,
)
from ..distributed.fleet.utils.recompute import recompute
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Dropout, Embedding
from ..nn.layer.container import LayerList
from ..nn.layer.norm import LayerNorm
from ..nn.layer_base import Layer, ParamAttr
from ..ops.linalg import matmul


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 0  # 0 -> 4*hidden
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    use_recompute: bool = False
    # scan-over-layers: run the (uniform) decoder stack as ONE lax.scan
    # over stacked per-layer params.  TPU-native big-model form: compile
    # time stops scaling with depth (the body compiles once) and, with
    # use_recompute, the scan's sequential backward ENFORCES one-layer-at-
    # a-time rematerialization — the unrolled form leaves the scheduler
    # free to float recomputed forwards early (measured ~1.9 GiB/layer
    # retained on the 6.7B AOT plan, docs/PERF.md).  No reference analog
    # (its static graphs unroll).
    scan_layers: bool = False
    fuse_qkv: bool = True
    activation: str = "gelu"
    # MoE (GPT-MoE / GShard-style FFN replacement): 0 = dense FFN
    moe_num_experts: int = 0
    moe_top_k: int = 0  # 0 = the gate's own default (gshard 2, switch 1)
    moe_every_n_layers: int = 2  # every n-th block becomes MoE
    moe_capacity_factor: float = 1.2
    moe_aux_loss_weight: float = 0.01
    moe_gate: str = "gshard"
    # fused LM-head + cross-entropy: the [B,T,V] logits never materialize
    # (chunked online-logsumexp, F.fused_linear_nll_loss).  Applies ONLY
    # to the TRAINING forward (model.training and single mp) — there
    # forward returns FusedHeadOutput(hidden, tied_weight) for the
    # criterion; eval/decode forwards always return logits.  Measured
    # −10% on gpt2-small/v5e (docs/PERF.md round-5 dead ends): opt-in for
    # large-vocab / HBM-constrained regimes, default off.
    fuse_head_loss: bool = False

    def __post_init__(self):
        if not self.intermediate_size:
            self.intermediate_size = 4 * self.hidden_size


# FleetX / GPT-3 paper ladder (vocab padded to a 128 multiple for MXU tiling)
GPT_CONFIGS = {
    "gpt-tiny": dict(vocab_size=1024, hidden_size=128, num_layers=2,
                     num_attention_heads=4, max_position_embeddings=256),
    "gpt2-small-en": dict(hidden_size=768, num_layers=12,
                          num_attention_heads=12),      # 125M
    "gpt2-medium-en": dict(hidden_size=1024, num_layers=24,
                           num_attention_heads=16),     # 345M
    "gpt2-large-en": dict(hidden_size=1536, num_layers=24,
                          num_attention_heads=16),      # 760M
    "gpt3-1.3B-en": dict(hidden_size=2048, num_layers=24,
                         num_attention_heads=16,
                         max_position_embeddings=2048),
    "gpt3-2.7B-en": dict(hidden_size=2560, num_layers=32,
                         num_attention_heads=32,
                         max_position_embeddings=2048),
    "gpt3-6.7B-en": dict(hidden_size=4096, num_layers=32,
                         num_attention_heads=32,
                         max_position_embeddings=2048),
    "gpt3-13B-en": dict(hidden_size=5120, num_layers=40,
                        num_attention_heads=40,
                        max_position_embeddings=2048),
}


def gpt_config(name: str, **overrides) -> GPTConfig:
    base = dict(GPT_CONFIGS[name])
    base.update(overrides)
    return GPTConfig(**base)


def _init_attr(std: float) -> ParamAttr:
    return ParamAttr(initializer=Normal(mean=0.0, std=std))


def _activation_spec() -> P:
    """Batch over the data axes, sequence over 'sep' (context parallelism —
    _constrain drops whichever axes the live mesh lacks)."""
    return P(("dcn", "dp", "sharding"), "sep", None)


# fused-qkv column layout versions: 1 = role-major [3, nh, hd] (round-1 /
# reference fused_attention_op.cu layout), 2 = head-major [nh, 3, hd]
QKV_LAYOUT_HEAD_MAJOR = 2


class GPTSelfAttention(Layer):
    """Causal self-attention: fused QKV column-parallel projection, flash
    attention core, row-parallel output projection — the TP structure of the
    reference's fused_attention_op.cu + mp_layers.py column/row pair."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        h, nh = config.hidden_size, config.num_attention_heads
        assert h % nh == 0
        self.num_heads = nh
        self.head_dim = h // nh
        self.mp_degree = max(_mp_info()[0], 1)
        assert nh % self.mp_degree == 0, (
            f"num heads {nh} not divisible by mp degree {self.mp_degree}")
        wa = _init_attr(config.initializer_range)
        self.qkv_proj = ColumnParallelLinear(
            h, 3 * h, weight_attr=wa, has_bias=True, gather_output=False)
        # reference scales the residual-path init by 1/sqrt(2*L)
        out_std = config.initializer_range / math.sqrt(
            2.0 * config.num_layers)
        self.out_proj = RowParallelLinear(
            h, h, weight_attr=_init_attr(out_std), has_bias=True,
            input_is_parallel=True)
        self.attn_dropout_prob = config.attention_dropout_prob
        # QKV interleaving must keep each head's q,k,v on the same mp shard.
        # The fused columns are grouped HEAD-major [nh, 3, hd] (vs the
        # reference's [3, nh, hd], fused_attention_op.cu): a contiguous
        # column shard is then a set of complete (q,k,v) head triples, so
        # the same weight layout serves both the GSPMD path (constraint on
        # the nh dim) and the explicit shard_map pipeline path where the
        # local shard is reshaped directly.
        # The fused-column layout is versioned: qkv_layout==2 means
        # head-major [nh, 3, hd]. Checkpoints without the marker (round-1
        # saves, reference exports) are role-major [3, nh, hd] and are
        # permuted on load by _state_dict_compat_ below.
        self.register_buffer(
            "qkv_layout",
            Tensor(jnp.asarray(QKV_LAYOUT_HEAD_MAJOR, jnp.int32),
                   _internal=True))

    # What a checkpoint WITHOUT a qkv_layout marker means.  Markerless
    # checkpoints are ambiguous: saves made after the head-major layout
    # landed but before the marker existed are head-major, while reference
    # exports (fused_attention_op.cu) are role-major.  Head-major is the
    # default because that is what every save from this codebase since the
    # layout change contains; set to "role_major" (class-wide) before
    # set_state_dict to import reference-layout fused qkv weights.
    markerless_qkv_layout = "head_major"

    def _state_dict_compat_(self, state, prefix):
        """Migrate role-major fused-qkv checkpoints (qkv_layout marker < 2,
        or markerless with markerless_qkv_layout == "role_major") to the
        head-major column layout in place."""
        wkey = prefix + "qkv_proj.weight"
        mkey = prefix + "qkv_layout"
        if wkey not in state:
            return
        marker = state.get(mkey)
        if marker is None:
            if self.markerless_qkv_layout != "role_major":
                # assume head-major (every post-layout-change save); stamp
                # the marker so the re-saved checkpoint is unambiguous
                state[mkey] = jnp.asarray(QKV_LAYOUT_HEAD_MAJOR, jnp.int32)
                return
        elif int(np.asarray(
                marker._value if hasattr(marker, "_value") else marker)) \
                >= QKV_LAYOUT_HEAD_MAJOR:
            return
        nh_hd = self.num_heads * self.head_dim

        def _permute(arr, is_bias):
            a = np.asarray(arr._value if hasattr(arr, "_value") else arr)
            if is_bias:
                return a.reshape(3, self.num_heads, self.head_dim) \
                        .transpose(1, 0, 2).reshape(3 * nh_hd)
            h = a.shape[0]
            return a.reshape(h, 3, self.num_heads, self.head_dim) \
                    .transpose(0, 2, 1, 3).reshape(h, 3 * nh_hd)

        state[wkey] = jnp.asarray(_permute(state[wkey], False))
        bkey = prefix + "qkv_proj.bias"
        if bkey in state:
            state[bkey] = jnp.asarray(_permute(state[bkey], True))
        state[mkey] = jnp.asarray(QKV_LAYOUT_HEAD_MAJOR, jnp.int32)

    def forward(self, x, cache=None, use_cache=False, pre_norm=None):
        b, t = x.shape[0], x.shape[1]
        if pre_norm is not None:
            # fused pre-LN -> qkv projection (kernels/ln_matmul.py); bias
            # stays outside the kernel so XLA fuses it downstream
            qkv = F.fused_ln_linear(
                x, pre_norm.weight, pre_norm.bias, self.qkv_proj.weight,
                self.qkv_proj.bias, eps=pre_norm._epsilon)
        else:
            qkv = self.qkv_proj(x)  # [B, T, 3H/mp-sharded]
        if use_cache or cache is not None:
            # batched multi-LoRA serving path (serving/adapters): when the
            # engine's jit entered an adapter scope, add the per-row
            # low-rank delta gathered by each row's adapter_id from the
            # stacked banks — fixed-shape operands, so the decode program
            # keeps its ONE compiled signature; rows at id 0 gather the
            # zero adapter (delta exactly 0.0: base rows stay exact)
            from ..serving.adapters.lora import active as _lora_active
            _scope = _lora_active()
            if _scope is not None:
                from ..core.tensor import Tensor as _T
                xv = x._value if isinstance(x, Tensor) else x
                qkv = _T(qkv._value + _scope.delta_qkv(xv), _internal=True)
        # under explicit shard_map (pipeline stage bodies) the mp axis is
        # bound and qkv is the LOCAL column shard: reshape over local heads
        nh = self.num_heads
        axis = getattr(self.qkv_proj.mp_group, "axis_name", None) or "mp"
        if self.mp_degree > 1 and mesh_mod.axis_bound(axis):
            from .._compat import bound_axis_size
            nh //= bound_axis_size(axis)
        qkv = qkv.reshape([b, t, nh, 3, self.head_dim])
        qkv = _constrain(qkv, P(_U, _U, "mp", _U, _U))
        if cache is None and not use_cache:
            # fused path: ONE whole-qkv transpose (fuses into the projection
            # matmul) instead of three per-operand layout copies at the
            # flash custom-call boundary (docs/PERF.md)
            out = F.fused_qkv_attention(
                qkv, dropout_p=self.attn_dropout_prob, is_causal=True,
                training=self.training)
        else:
            q, k, v = (qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2])
            new_cache = None
            if cache is not None and len(cache) in (3, 4, 5, 6):
                # STATIC cache (k_buf [B,L,nh,hd], v_buf, length): write the
                # new keys/values in place at `length` and attend over the
                # fixed-shape buffer under an explicit validity mask — every
                # decode step is ONE compiled program with donated buffers
                # (the AnalysisPredictor zero-copy run analog,
                # analysis_predictor.cc:1618), instead of a concat that
                # gives each position its own XLA shape.
                # The 5-tuple form (k_buf, v_buf, length, k_scale, v_scale)
                # is the int8-quantized pool (serving kv_dtype="int8"):
                # buffers store int8, scales [B, L] carry one absmax scale
                # per cached row; writes quantize, the attention read
                # dequantizes inline (kv_quant helpers).
                # The PAGED forms (serving paged_kv=True) add an int32
                # page table at index 3: 4-tuple (k_pages, v_pages,
                # lengths, page_table) and 6-tuple (..., k_scale,
                # v_scale).  K/V live as [num_pages, page_size, heads,
                # head_dim] pages; position p of row b maps to
                # pages[page_table[b, p // P], p % P].  Writes scatter
                # through the table (sentinel/out-of-range entries DROP
                # — unallocated virtual positions are unwritable), reads
                # gather the row's pages back into a [B, L_virt, ...]
                # view under the same validity mask as the dense pool —
                # the page table is just one more fixed-shape operand,
                # so decode keeps its ONE compiled signature.
                import jax.numpy as jnp

                from ..core.tensor import Tensor as _T
                k_buf, v_buf, pos0 = cache[0], cache[1], cache[2]
                quantized = len(cache) in (5, 6)
                paged = len(cache) in (4, 6)
                k_raw = k_buf._value if isinstance(k_buf, _T) else k_buf
                v_raw = v_buf._value if isinstance(v_buf, _T) else v_buf
                start = jnp.asarray(pos0, jnp.int32)
                if (quantized or paged) and start.ndim != 1:
                    raise ValueError(
                        "int8 (5/6-tuple) and paged (4/6-tuple) KV "
                        "caches are supported only in the per-slot "
                        "vector-length form the serving engine uses")
                if start.ndim == 1:
                    # PER-SLOT lengths (continuous batching, serving.Engine):
                    # `pos0` is a [B] vector — every row owns a slot in a
                    # shared pool and sits at its own position, so the new
                    # keys/values scatter to per-row offsets and attention
                    # runs under a per-row validity mask.  Rows whose write
                    # would fall off the buffer end (an inactive slot parked
                    # at max_len) are dropped by the scatter, never clipped
                    # onto a live row.  t may be > 1 (speculative
                    # verification / prefix-tail prefill): position j of a
                    # row writes at its own offset + j and attends causally
                    # within the new span.
                    scale_i = 4 if paged else 3
                    att_out = None
                    if quantized:
                        from ..serving.kv_quant import (dequantize_pool,
                                                        quantize_rows)
                        ks_raw, vs_raw = cache[scale_i], cache[scale_i + 1]
                        ks_raw = (ks_raw._value if isinstance(ks_raw, _T)
                                  else ks_raw)
                        vs_raw = (vs_raw._value if isinstance(vs_raw, _T)
                                  else vs_raw)
                        kq, ksc = quantize_rows(k._value)
                        vq, vsc = quantize_rows(v._value)
                    if paged:
                        # gather/scatter through the page table: position
                        # p of row r lives at pages[table[r, p // P],
                        # p % P].  Sentinel table entries (>= num_pages)
                        # make the scatter DROP (an unallocated or
                        # parked position is unwritable) and gather a
                        # clamped garbage page that the validity mask
                        # excludes from attention.
                        pt = cache[3]
                        pt = pt._value if isinstance(pt, _T) else pt
                        pt = jnp.asarray(pt, jnp.int32)
                        n_pages, psz = k_raw.shape[0], k_raw.shape[1]
                        n_pt = pt.shape[1]
                        virt = n_pt * psz
                        rows = jnp.arange(pt.shape[0])[:, None]
                        cols = start[:, None] + jnp.arange(t)[None, :]
                        pslot = jnp.clip(cols // psz, 0, n_pt - 1)
                        pid = jnp.where(cols < virt, pt[rows, pslot],
                                        n_pages)
                        off = cols % psz
                        if quantized:
                            k_raw = k_raw.at[pid, off].set(kq, mode="drop")
                            v_raw = v_raw.at[pid, off].set(vq, mode="drop")
                            ks_raw = ks_raw.at[pid, off].set(ksc,
                                                             mode="drop")
                            vs_raw = vs_raw.at[pid, off].set(vsc,
                                                             mode="drop")
                        else:
                            k_raw = k_raw.at[pid, off].set(
                                k._value.astype(k_raw.dtype), mode="drop")
                            v_raw = v_raw.at[pid, off].set(
                                v._value.astype(v_raw.dtype), mode="drop")
                        # serving decode with Engine(decode_kernel=
                        # "pallas"): the attention READ runs as the fused
                        # Pallas kernel — page-table walk + (int8) dequant
                        # + masked softmax in one custom call, no
                        # [B, virt, ...] gather temp.  The write scatter
                        # above is unchanged, so the kernel attends over
                        # the post-write pool exactly like the XLA read.
                        from ..kernels.paged_attention import (
                            active as _paged_kernel_active)
                        if _paged_kernel_active():
                            from ..kernels.paged_attention import (
                                paged_decode_attention)
                            att_out = paged_decode_attention(
                                q._value, k_raw, v_raw, pt, start,
                                k_scale=ks_raw if quantized else None,
                                v_scale=vs_raw if quantized else None)
                        elif quantized:
                            pt_safe = jnp.clip(pt, 0, n_pages - 1)
                            k_att = dequantize_pool(
                                k_raw[pt_safe].reshape(
                                    (pt.shape[0], virt) + k_raw.shape[2:]),
                                ks_raw[pt_safe].reshape(pt.shape[0], virt),
                                k._value.dtype)
                            v_att = dequantize_pool(
                                v_raw[pt_safe].reshape(
                                    (pt.shape[0], virt) + v_raw.shape[2:]),
                                vs_raw[pt_safe].reshape(pt.shape[0], virt),
                                v._value.dtype)
                        else:
                            pt_safe = jnp.clip(pt, 0, n_pages - 1)
                            k_att = k_raw[pt_safe].reshape(
                                (pt.shape[0], virt) + k_raw.shape[2:])
                            v_att = v_raw[pt_safe].reshape(
                                (pt.shape[0], virt) + v_raw.shape[2:])
                        att_len = virt
                    else:
                        rows = jnp.arange(k_raw.shape[0])[:, None]
                        cols = start[:, None] + jnp.arange(t)[None, :]
                        if quantized:
                            k_raw = k_raw.at[rows, cols].set(kq,
                                                             mode="drop")
                            v_raw = v_raw.at[rows, cols].set(vq,
                                                             mode="drop")
                            ks_raw = ks_raw.at[rows, cols].set(ksc,
                                                               mode="drop")
                            vs_raw = vs_raw.at[rows, cols].set(vsc,
                                                               mode="drop")
                            k_att = dequantize_pool(k_raw, ks_raw,
                                                    k._value.dtype)
                            v_att = dequantize_pool(v_raw, vs_raw,
                                                    v._value.dtype)
                        else:
                            k_raw = k_raw.at[rows, cols].set(
                                k._value.astype(k_raw.dtype), mode="drop")
                            v_raw = v_raw.at[rows, cols].set(
                                v._value.astype(v_raw.dtype), mode="drop")
                            k_att, v_att = k_raw, v_raw
                        att_len = k_raw.shape[1]
                    if att_out is not None:
                        out = _T(att_out, _internal=True)
                    else:
                        mask = (jnp.arange(att_len)[None, None, :] <=
                                cols[:, :, None])  # [B,t,L] causal+validity
                        out = F.scaled_dot_product_attention(
                            q, _T(k_att, _internal=True),
                            _T(v_att, _internal=True),
                            attn_mask=_T(mask[:, None], _internal=True),
                            dropout_p=0.0, is_causal=False, training=False)
                    out = out.reshape([b, t, nh * self.head_dim])
                    out = _constrain(out, P(_U, _U, "mp"))
                    out = self.out_proj(out)
                    new_cache = (_T(k_raw, _internal=True),
                                 _T(v_raw, _internal=True), start + t)
                    if paged:
                        new_cache = new_cache + (cache[3],)
                    if quantized:
                        new_cache = new_cache + (
                            _T(ks_raw, _internal=True),
                            _T(vs_raw, _internal=True))
                    if use_cache:
                        return out, new_cache
                    return out
                z = jnp.zeros((), jnp.int32)
                k_raw = jax.lax.dynamic_update_slice(
                    k_raw, k._value.astype(k_raw.dtype), (z, start, z, z))
                v_raw = jax.lax.dynamic_update_slice(
                    v_raw, v._value.astype(v_raw.dtype), (z, start, z, z))
                if isinstance(pos0, int) and pos0 == 0:
                    # static prefill (helper builds the cache inside the
                    # prefill jit with a PYTHON-int length 0): no past to
                    # attend over, so the prompt keeps the causal
                    # flash-attention path instead of dense masked
                    # attention over the zero-padded buffer
                    out = F.scaled_dot_product_attention(
                        q, k, v, dropout_p=0.0, is_causal=True,
                        training=False)
                else:
                    max_len = k_raw.shape[1]
                    qpos = start + jnp.arange(t)
                    mask = (jnp.arange(max_len)[None, :] <=
                            qpos[:, None])        # [t, L] causal + validity
                    out = F.scaled_dot_product_attention(
                        q, _T(k_raw, _internal=True),
                        _T(v_raw, _internal=True),
                        attn_mask=_T(mask[None, None], _internal=True),
                        dropout_p=0.0, is_causal=False, training=False)
                new_cache = (_T(k_raw, _internal=True),
                             _T(v_raw, _internal=True), start + t)
            else:
                if cache is not None:
                    # growing-concat cache: every decode step has a new
                    # key length, so a jitted caller retraces per token —
                    # the sentinel points at the static path once
                    from ..observability.retrace import (
                        note_dynamic_cache_growth)
                    note_dynamic_cache_growth("models.gpt.GPTSelfAttention")
                    from ..ops.manipulation import concat
                    k = concat([cache[0], k], axis=1)
                    v = concat([cache[1], v], axis=1)
                out = F.scaled_dot_product_attention(
                    q, k, v, dropout_p=self.attn_dropout_prob,
                    is_causal=True, training=self.training)
            out = out.reshape([b, t, nh * self.head_dim])
        out = _constrain(out, P(_U, _U, "mp"))
        out = self.out_proj(out)
        if use_cache:
            return out, (new_cache if new_cache is not None else (k, v))
        return out


class GPTMLP(Layer):
    """Column→Row parallel FFN (reference fused_feedforward_op.cu shape)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        h, ffn = config.hidden_size, config.intermediate_size
        out_std = config.initializer_range / math.sqrt(2.0 * config.num_layers)
        self.fc0 = ColumnParallelLinear(
            h, ffn, weight_attr=_init_attr(config.initializer_range),
            has_bias=True, gather_output=False)
        self.fc1 = RowParallelLinear(
            ffn, h, weight_attr=_init_attr(out_std), has_bias=True,
            input_is_parallel=True)
        self.act = getattr(F, config.activation)

    def forward(self, x, pre_norm=None):
        if pre_norm is not None:
            h = F.fused_ln_linear(x, pre_norm.weight, pre_norm.bias,
                                  self.fc0.weight, self.fc0.bias,
                                  eps=pre_norm._epsilon)
        else:
            h = self.fc0(x)
        return self.fc1(self.act(h))


class GPTMoEMLP(Layer):
    """GShard-style FFN: the dense MLP becomes a mixture of expert MLPs with
    capacity-based token dispatch (GPT-MoE / FleetX moe recipe; backed by
    incubate MoELayer → all_to_all over the expert axis when bound).  The
    gate's balance loss is surfaced via `last_aux_loss` and folded into the
    LM loss by GPTForPretraining."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        from ..incubate.distributed.models.moe import MoELayer

        cf = config.moe_capacity_factor
        gate = {"type": config.moe_gate}
        fixed_k = {"gshard": 2, "switch": 1}.get(config.moe_gate)
        if fixed_k is None:
            gate["top_k"] = config.moe_top_k or 2
        elif config.moe_top_k not in (0, fixed_k):
            raise ValueError(
                f"moe_gate={config.moe_gate!r} requires moe_top_k={fixed_k} "
                f"(got {config.moe_top_k}); use moe_gate='naive' for other k")
        if config.moe_gate in ("gshard", "switch"):
            gate["capacity"] = (cf, 2 * cf)  # train/eval caps the gate uses
        self.moe = MoELayer(
            config.hidden_size,
            [GPTMLP(config) for _ in range(config.moe_num_experts)],
            gate=gate, capacity_factor=cf)
        self.last_aux_loss = None

    def forward(self, x):
        out = self.moe(x)
        self.last_aux_loss = self.moe.gate.get_loss()
        return out


class GPTDecoderLayer(Layer):
    """Pre-LN transformer block (the GPT-2/3 arrangement the reference's
    FusedMultiTransformer implements with normalize_before=True)."""

    def __init__(self, config: GPTConfig, use_moe: bool = False):
        super().__init__()
        eps = config.layer_norm_epsilon
        self.norm1 = LayerNorm(config.hidden_size, epsilon=eps)
        self.self_attn = GPTSelfAttention(config)
        self.norm2 = LayerNorm(config.hidden_size, epsilon=eps)
        self.mlp = GPTMoEMLP(config) if use_moe else GPTMLP(config)
        self.dropout1 = Dropout(config.hidden_dropout_prob)
        self.dropout2 = Dropout(config.hidden_dropout_prob)

    def _fuse_ln_proj(self):
        """Route the pre-LNs INTO their consuming projections (one pallas
        ln->matmul custom call per projection) when the opt-in kernel
        applies — single device, dense MLP, no KV cache."""
        from ..kernels.ln_matmul import ln_matmul_enabled
        return (ln_matmul_enabled() and self.self_attn.mp_degree <= 1
                and mesh_mod.get_global_mesh() is None
                and not isinstance(self.mlp, GPTMoEMLP))

    def forward(self, x, cache=None, use_cache=False):
        residual = x
        if not use_cache and self._fuse_ln_proj():
            y = self.self_attn(x, pre_norm=self.norm1)
            x = residual + self.dropout1(y)
            residual = x
            y = self.mlp(x, pre_norm=self.norm2)
            return residual + self.dropout2(y)
        y = self.norm1(x)
        if use_cache:
            y, new_cache = self.self_attn(y, cache=cache, use_cache=True)
        else:
            y = self.self_attn(y)
            new_cache = None
        x = residual + self.dropout1(y)
        residual = x
        y = self.mlp(self.norm2(x))
        x = residual + self.dropout2(y)
        if use_cache:
            return x, new_cache
        return x


class GPTEmbeddings(Layer):
    """Word (vocab-parallel) + learned position embeddings."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        wa = _init_attr(config.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size, weight_attr=wa)
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size, weight_attr=wa)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, position_ids=None):
        if position_ids is None:
            from ..ops.creation import arange
            t = input_ids.shape[1]
            position_ids = arange(0, t, dtype="int64").reshape([1, t])
        w = self.word_embeddings(input_ids)
        p = self.position_embeddings(position_ids)
        return self.dropout(w + p)


class GPTModel(Layer):
    """The transformer stack.  Output: hidden states [B, T, H]."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = LayerList(
            [GPTDecoderLayer(
                config,
                use_moe=(config.moe_num_experts > 0 and
                         (i + 1) % max(config.moe_every_n_layers, 1) == 0))
             for i in range(config.num_layers)])
        self.final_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None, caches=None,
                use_cache=False):
        use_cache = use_cache or caches is not None
        if caches is None and use_cache:
            caches = [None] * len(self.layers)
        if position_ids is None and use_cache and caches[0] is not None:
            # incremental decode: offset positions by the cached key length
            t = input_ids.shape[1]
            if len(caches[0]) in (3, 4, 5, 6):
                # static cache (k_buf, v_buf, length[, page_table]
                # [, k_scale, v_scale]): position base may be a python int
                # (static prefill) or a traced scalar (step); every tuple
                # form keeps length at [2]
                import jax.numpy as jnp

                from ..core.tensor import Tensor as _T
                past = jnp.asarray(caches[0][2], jnp.int64)
                if past.ndim == 1:
                    # per-slot lengths: each row decodes at its own position
                    pos = past[:, None] + jnp.arange(t, dtype=jnp.int64)
                else:
                    pos = (past +
                           jnp.arange(t, dtype=jnp.int64)).reshape(1, t)
                position_ids = _T(pos, _internal=True)
            else:
                from ..ops.creation import arange
                past = caches[0][0].shape[1]
                position_ids = arange(past, past + t,
                                      dtype="int64").reshape([1, t])
        x = self.embeddings(input_ids, position_ids)
        x = _constrain(x, _activation_spec())
        new_caches = [] if use_cache else None
        if self.config.scan_layers and not use_cache and \
                self.config.moe_num_experts == 0:
            x = self._scan_layers(x)
        else:
            _scope = None
            if use_cache:
                # advance the batched-adapter scope's layer cursor as the
                # stack walks (each layer gathers ITS bank slice)
                from ..serving.adapters.lora import active as _lora_active
                _scope = _lora_active()
            for i, layer in enumerate(self.layers):
                if use_cache:
                    if _scope is not None:
                        _scope.layer = i
                    x, c = layer(x, cache=caches[i], use_cache=True)
                    new_caches.append(c)
                elif self.config.use_recompute and self.training and \
                        not isinstance(layer.mlp, GPTMoEMLP):
                    # MoE layers run outside remat: the recorded gate aux
                    # loss would otherwise leak a jax.checkpoint tracer
                    x = recompute(layer, x)
                else:
                    x = layer(x)
        x = self.final_norm(x)
        if use_cache:
            return x, new_caches
        return x

    def _scan_layers(self, x):
        """Uniform decoder stack as ONE lax.scan over stacked per-layer
        params; body optionally under jax.checkpoint (see
        GPTConfig.scan_layers).  Parameters stay per-layer objects (state
        dict / checkpoint layout unchanged); the stack happens at trace
        time and autodiff routes layer grads back through it."""
        from ..core import random as random_mod
        from ..nn.functional_call import functional_call

        template = self.layers[0]
        sds = [layer.state_dict() for layer in self.layers]
        param_names = {k for k, _ in template.named_parameters()}
        stacked, static_vals = {}, {}
        for k in sds[0]:
            if k in param_names:
                stacked[k] = jnp.stack([sd[k]._value for sd in sds])
            else:
                # non-param buffers (layout markers) are identical across
                # layers; bind layer 0's
                static_vals[k] = sds[0][k]._value
        base_key = random_mod.next_key()
        xs = (jnp.arange(len(self.layers)), stacked)

        def body(h, sl):
            idx, vals = sl
            values = dict(static_vals)
            values.update(vals)
            # per-layer RNG stream (dropout masks must differ by depth)
            with random_mod.push_key(jax.random.fold_in(base_key, idx)):
                out, _ = functional_call(template, values,
                                         (Tensor(h, _internal=True),))
            return (out._value if isinstance(out, Tensor) else out), None

        if self.config.use_recompute and self.training:
            body = jax.checkpoint(body)
        h0 = x._value if isinstance(x, Tensor) else x
        h, _ = jax.lax.scan(body, h0, xs)
        return Tensor(h, _internal=True)

    def moe_aux_loss(self):
        """Sum of gate balance losses from the last forward (None when the
        model has no MoE layers, or when the last forward ran inside a
        now-finished trace — the compiled step consumes the aux loss inside
        its own program, so a stale tracer outside it is meaningless)."""
        import jax

        total = None
        try:
            for layer in self.layers:
                aux = getattr(layer.mlp, "last_aux_loss", None)
                if aux is not None:
                    total = aux if total is None else total + aux
            if total is not None:
                total._value + 0  # probe: stale tracers raise here
        except jax.errors.UnexpectedTracerError:
            return None
        return total


class FusedHeadOutput(tuple):
    """(hidden, head_weight) marker the pretraining criterion consumes via
    F.fused_linear_nll_loss — produced when config.fuse_head_loss."""

    def __new__(cls, hidden, weight):
        return super().__new__(cls, (hidden, weight))


class GPTForPretraining(Layer):
    """LM head tied to the (vocab-parallel) word embedding — logits are
    vocab-sharded over 'mp', consumed by ParallelCrossEntropy without ever
    gathering the [B,T,V] tensor (the reference's
    c_softmax_with_cross_entropy_op.cu pattern)."""

    def __init__(self, gpt: GPTModel):
        super().__init__()
        self.gpt = gpt

    def forward(self, input_ids, position_ids=None, caches=None,
                use_cache=False):
        if use_cache or caches is not None:
            x, new_caches = self.gpt(input_ids, position_ids, caches=caches,
                                     use_cache=True)
            return self.lm_head(x), new_caches
        x = self.gpt(input_ids, position_ids)
        if self.gpt.config.fuse_head_loss and self.training \
                and max(_mp_info()[0], 1) == 1:
            # hand the criterion (hidden, tied weight) instead of logits so
            # the head matmul fuses into the chunked CE (the [B,T,V]
            # tensor never exists); under mp the vocab-parallel
            # ParallelCrossEntropy path already avoids the gather.
            # Traced (functional_call) path: the weight's traced VALUE is
            # captured into a fresh Tensor — the state swap restores the
            # parameter object in place on exit, so returning the param
            # itself would hand the criterion the CONCRETE weights
            # (constant under jax.grad — the tied head grad would
            # silently vanish).  Eager path: the detached copy is the bug
            # — loss.backward() would never reach the tied table — so the
            # parameter itself rides on the tape.
            w = self.gpt.embeddings.word_embeddings.weight
            if isinstance(w._value, jax.core.Tracer):
                return FusedHeadOutput(x, Tensor(w._value, _internal=True))
            return FusedHeadOutput(x, w)
        return self.lm_head(x)

    def lm_head(self, hidden_states):
        w = self.gpt.embeddings.word_embeddings.weight
        logits = matmul(hidden_states, w, transpose_y=True)
        return _constrain(logits, P(("dcn", "dp", "sharding"), None, "mp"))

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id=None, temperature: float = 0.0, top_k: int = 0,
                 seed: int = 0, max_slots: int = 8,
                 timeout_s: float = 600.0, **engine_kwargs) -> np.ndarray:
        """Batch generation built on the continuous-batching serving engine
        (paddle_tpu.serving.Engine): each row becomes one request over a
        shared slot pool, so generation and the serving path are the SAME
        code.  Returns [batch, prompt + longest] ids; rows that stopped at
        `eos_token_id` are right-padded with it (0 when no eos is set).
        Extra keyword args reach the Engine — the decode fast-path knobs
        (``kv_dtype="int8"``, ``speculative_k=``, ``prefix_cache=``,
        ``sample_on_device=``, ``decode_kernel="pallas"`` with
        ``paged_kv=True``) apply to offline generation too."""
        from ..serving import Engine

        ids = np.asarray(input_ids._value if isinstance(input_ids, Tensor)
                         else input_ids).astype(np.int64)
        if ids.ndim == 1:
            ids = ids[None]
        b, t = ids.shape
        engine = Engine(self, max_slots=min(int(max_slots), b),
                        max_len=t + int(max_new_tokens), **engine_kwargs)
        try:
            handles = [engine.submit(row, max_new_tokens=max_new_tokens,
                                     eos_token_id=eos_token_id,
                                     temperature=temperature, top_k=top_k,
                                     seed=seed + i)
                       for i, row in enumerate(ids)]
            gen = [h.result(timeout=timeout_s) for h in handles]
        finally:
            engine.shutdown()
        width = max(len(g) for g in gen)
        pad = 0 if eos_token_id is None else int(eos_token_id)
        out = np.full((b, t + width), pad, np.int64)
        out[:, :t] = ids
        for i, g in enumerate(gen):
            out[i, t:t + len(g)] = g
        return out


class GPTPretrainingCriterion(Layer):
    """Masked next-token cross entropy (FleetX pretraining loss)."""

    def __init__(self, topo=None, ignore_index=-100):
        super().__init__()
        mp_degree = max(_mp_info()[0], 1)
        self.mp = mp_degree > 1
        self.ignore_index = ignore_index
        self.parallel_loss = (ParallelCrossEntropy(ignore_index=ignore_index)
                              if self.mp else None)

    def forward(self, prediction_scores, masked_lm_labels, loss_mask=None):
        if isinstance(prediction_scores, FusedHeadOutput):
            hidden, w = prediction_scores
            loss = F.fused_linear_nll_loss(hidden, w, masked_lm_labels,
                                           ignore_index=self.ignore_index)
        elif self.parallel_loss is not None:
            loss = self.parallel_loss(prediction_scores, masked_lm_labels)
        else:
            loss = F.fused_nll_loss(prediction_scores, masked_lm_labels,
                                    ignore_index=self.ignore_index)
        loss = loss.reshape([-1]).astype("float32")
        if loss_mask is not None:
            m = loss_mask.reshape([-1]).astype("float32")
            return (loss * m).sum() / m.sum().clip(min=1.0)
        return loss.mean()


class GPTHeadPipe(Layer):
    """Last pipeline stage: final norm + (untied) vocab-parallel LM head.
    The tied-weight head needs the embedding table on the same stage, which
    the explicit pipeline schedule can't provide — FleetX's PP GPT recipe
    likewise unties or all-reduces the shared grads (SharedLayerDesc); here
    untied.  Under mp the head column-shards the vocab dim so the [B,T,V]
    logits stay mp-sharded for ParallelCrossEntropy."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.final_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_epsilon)
        self.lm_head = ColumnParallelLinear(
            config.hidden_size, config.vocab_size,
            weight_attr=_init_attr(config.initializer_range),
            has_bias=False, gather_output=False)

    def forward(self, x):
        logits = self.lm_head(self.final_norm(x))
        return _constrain(logits, P(("dcn", "dp", "sharding"), None, "mp"))


def gpt_pipeline_descs(config: GPTConfig):
    """LayerDesc list for fleet.PipelineLayer — the FleetX GPT PP recipe
    shape (embeddings | N decoder layers | norm+head); a uniform decoder run
    is what the explicit GPipe schedule stacks over the pipe axis.  MoE
    configs produce their MoE layers here too (structurally non-uniform
    stages then take the one-program GSPMD pipeline path).  Recompute is a
    PipelineLayer concern: pass recompute_interval=1 to PipelineLayer when
    config.use_recompute is set."""
    from ..distributed.fleet.meta_parallel.parallel_layers.pp_layers import (
        LayerDesc)

    return ([LayerDesc(GPTEmbeddings, config)] +
            [LayerDesc(
                GPTDecoderLayer, config,
                use_moe=(config.moe_num_experts > 0 and
                         (i + 1) % max(config.moe_every_n_layers, 1) == 0))
             for i in range(config.num_layers)] +
            [LayerDesc(GPTHeadPipe, config)])


class GPTMoEPretrainingCriterion(Layer):
    """LM loss + weighted MoE gate balance loss (the GShard/GPT-MoE training
    objective).  Reads the aux loss the model recorded during ITS forward in
    the same trace, so it works eagerly and inside the compiled step."""

    def __init__(self, model, aux_loss_weight=None, ignore_index=-100):
        super().__init__()
        # read-only reference: bypass Layer registration so the criterion
        # never claims the model's parameters/state as its own
        gpt = getattr(model, "gpt", model)
        object.__setattr__(self, "_gpt", gpt)
        w = aux_loss_weight
        if w is None:
            w = getattr(gpt, "config", None)
            w = w.moe_aux_loss_weight if w is not None else 0.01
        self.aux_weight = w
        self.lm = GPTPretrainingCriterion(ignore_index=ignore_index)

    def forward(self, prediction_scores, masked_lm_labels, loss_mask=None):
        loss = self.lm(prediction_scores, masked_lm_labels, loss_mask)
        aux = self._gpt.moe_aux_loss()
        if aux is not None:
            loss = loss + self.aux_weight * aux
        return loss


def build_gpt(name_or_config="gpt-tiny", for_pretraining=True, **overrides):
    if isinstance(name_or_config, GPTConfig):
        import dataclasses
        if "hidden_size" in overrides and "intermediate_size" not in overrides:
            # let __post_init__ recompute 4*hidden instead of copying the
            # stale materialized width
            overrides["intermediate_size"] = 0
        cfg = (dataclasses.replace(name_or_config, **overrides)
               if overrides else name_or_config)
    else:
        cfg = gpt_config(name_or_config, **overrides)
    model = GPTModel(cfg)
    if for_pretraining:
        return GPTForPretraining(model)
    return model


def gpt_num_params(cfg: GPTConfig) -> int:
    h, L, V, T = (cfg.hidden_size, cfg.num_layers, cfg.vocab_size,
                  cfg.max_position_embeddings)
    per_layer = 4 * h * h + 4 * h + 2 * h * cfg.intermediate_size \
        + cfg.intermediate_size + h + 4 * h  # attn + mlp + 2 LN
    return V * h + T * h + L * per_layer + 2 * h


def gpt_train_flops_per_token(cfg: GPTConfig, seq_len: int) -> float:
    """6*N + 12*L*h*s — the standard train-MFU accounting (fwd+bwd = 3x fwd;
    fwd matmuls = 2*N per token; the 12*L*h*s attention term already carries
    the 3x and the QK^T+AV pair)."""
    return (6.0 * gpt_num_params(cfg) +
            12.0 * cfg.num_layers * cfg.hidden_size * seq_len)
