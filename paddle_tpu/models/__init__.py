"""Flagship model families built on the framework (GPT, BERT/ERNIE;
vision detection configs follow the same pattern)."""
from .bert import (  # noqa: F401
    BERT_CONFIGS,
    BertConfig,
    BertForPretraining,
    BertModel,
    BertPretrainingCriterion,
    ErnieConfig,
    ErnieForPretraining,
    ErnieModel,
    bert_config,
    build_bert,
    build_ernie,
)
from .gpt import (  # noqa: F401
    GPT_CONFIGS,
    GPTConfig,
    GPTDecoderLayer,
    GPTEmbeddings,
    GPTForPretraining,
    GPTMoEMLP,
    GPTMoEPretrainingCriterion,
    GPTModel,
    GPTPretrainingCriterion,
    build_gpt,
    gpt_config,
    gpt_pipeline_descs,
    gpt_num_params,
    gpt_train_flops_per_token,
)
