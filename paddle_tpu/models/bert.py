"""BERT / ERNIE model family — parity with the reference's transformer
encoder stack (python/paddle/nn/layer/transformer.py TransformerEncoder used
by PaddleNLP's BertModel/ErnieModel recipes; pretraining heads follow the
BERT paper MLM+NSP layout the FleetX configs train).

TPU-first structure mirrors models/gpt.py: fused column-parallel QKV,
row-parallel output projections, flash-attention core, everything jittable
for the SPMD step builder.  ERNIE 3.0-class models are config presets of the
same encoder (their differences — knowledge masking, task ids — enter
through data and the extra task-type embedding, included here).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

from jax.sharding import PartitionSpec as P

from ..distributed.fleet.layers.mpu.mp_layers import (
    ColumnParallelLinear, RowParallelLinear,
    VocabParallelEmbedding, _constrain, _mp_info)
from ..nn import functional as F
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.norm import LayerNorm
from ..nn.layer_base import Layer
from ..nn.initializer import Normal
from ..nn.layer_base import ParamAttr

_U = P.UNCONSTRAINED


def _init_attr(std):
    return ParamAttr(initializer=Normal(mean=0.0, std=std))


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    task_type_vocab_size: int = 0  # >0 = ERNIE task-type embedding
    activation: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    layer_norm_epsilon: float = 1e-12
    initializer_range: float = 0.02
    pad_token_id: int = 0


BERT_CONFIGS = {
    "bert-tiny": dict(vocab_size=1024, hidden_size=128, num_layers=2,
                      num_attention_heads=2, intermediate_size=512,
                      max_position_embeddings=128),
    "bert-base-uncased": dict(),
    "bert-large-uncased": dict(hidden_size=1024, num_layers=24,
                               num_attention_heads=16,
                               intermediate_size=4096),
    "ernie-3.0-medium": dict(vocab_size=40000, hidden_size=768,
                             num_layers=6, num_attention_heads=12,
                             intermediate_size=3072, task_type_vocab_size=3),
    "ernie-3.0-base": dict(vocab_size=40000, hidden_size=768, num_layers=12,
                           num_attention_heads=12, intermediate_size=3072,
                           task_type_vocab_size=3),
}


def bert_config(name: str, **overrides) -> BertConfig:
    if name not in BERT_CONFIGS:
        raise KeyError(f"unknown config {name!r}; have "
                       f"{sorted(BERT_CONFIGS)}")
    kw = dict(BERT_CONFIGS[name])
    kw.update(overrides)
    return BertConfig(**kw)


class BertSelfAttention(Layer):
    """Bidirectional attention, fused QKV column-parallel + row-parallel out
    (same TP split as GPTSelfAttention, minus causality)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        h, nh = config.hidden_size, config.num_attention_heads
        assert h % nh == 0
        self.num_heads = nh
        self.head_dim = h // nh
        self.mp_degree = max(_mp_info()[0], 1)
        assert nh % self.mp_degree == 0
        wa = _init_attr(config.initializer_range)
        self.qkv_proj = ColumnParallelLinear(
            h, 3 * h, weight_attr=wa, has_bias=True, gather_output=False)
        out_std = config.initializer_range / math.sqrt(
            2.0 * config.num_layers)
        self.out_proj = RowParallelLinear(
            h, h, weight_attr=_init_attr(out_std), has_bias=True,
            input_is_parallel=True)
        self.attn_dropout_prob = config.attention_dropout_prob

    def forward(self, x, attn_mask=None):
        b, t = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)
        qkv = qkv.reshape([b, t, 3, self.num_heads, self.head_dim])
        qkv = _constrain(qkv, P(_U, _U, _U, "mp", _U))
        q, k, v = (qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_prob,
            is_causal=False, training=self.training)
        out = out.reshape([b, t, self.num_heads * self.head_dim])
        out = _constrain(out, P(_U, _U, "mp"))
        return self.out_proj(out)


class BertLayer(Layer):
    """Post-LN encoder block (the original BERT arrangement)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        eps = config.layer_norm_epsilon
        h, ffn = config.hidden_size, config.intermediate_size
        out_std = config.initializer_range / math.sqrt(
            2.0 * config.num_layers)
        self.self_attn = BertSelfAttention(config)
        self.norm1 = LayerNorm(h, epsilon=eps)
        self.fc0 = ColumnParallelLinear(
            h, ffn, weight_attr=_init_attr(config.initializer_range),
            has_bias=True, gather_output=False)
        self.fc1 = RowParallelLinear(
            ffn, h, weight_attr=_init_attr(out_std), has_bias=True,
            input_is_parallel=True)
        self.norm2 = LayerNorm(h, epsilon=eps)
        self.act = getattr(F, config.activation)
        self.dropout1 = Dropout(config.hidden_dropout_prob)
        self.dropout2 = Dropout(config.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        y = self.self_attn(x, attn_mask=attn_mask)
        x = self.norm1(x + self.dropout1(y))
        y = self.fc1(self.act(self.fc0(x)))
        return self.norm2(x + self.dropout2(y))


class BertEmbeddings(Layer):
    """word (vocab-parallel) + position + token-type (+ ERNIE task-type)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        wa = _init_attr(config.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size, weight_attr=wa)
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=wa)
        self.token_type_embeddings = Embedding(
            max(config.type_vocab_size, 1), config.hidden_size,
            weight_attr=wa)
        # no None pre-assignment: a plain instance attr would shadow the
        # registered sublayer (Layer.__getattr__ is only a fallback)
        if config.task_type_vocab_size > 0:
            self.task_type_embeddings = Embedding(
                config.task_type_vocab_size, config.hidden_size,
                weight_attr=wa)
        self._has_task_types = config.task_type_vocab_size > 0
        self.norm = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        from ..ops.creation import arange, zeros_like

        t = input_ids.shape[1]
        if position_ids is None:
            position_ids = arange(0, t, dtype="int64").reshape([1, t])
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        from ..distributed import mesh as _mesh_mod
        if position_ids.shape[0] == 1 and input_ids.shape[0] != 1 and \
                _mesh_mod.get_global_mesh() is not None:
            # expand the [1, T] position row to the full batch BEFORE the
            # lookup: a [1, T, H] broadcast operand picks up a degenerate
            # batch sharding from GSPMD propagation (its size-1 dim split
            # across the whole dp x sharding axis) and the backward
            # cotangent then pays a replicate-then-partition ("Involuntary
            # full rematerialization"); the batched lookup shards cleanly
            # like the token-type path
            position_ids = position_ids + zeros_like(input_ids)
        x = (self.word_embeddings(input_ids) +
             self.position_embeddings(position_ids) +
             self.token_type_embeddings(token_type_ids))
        if self._has_task_types:
            if task_type_ids is None:  # default task 0 like the reference
                task_type_ids = zeros_like(input_ids)
            x = x + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.norm(x))


class BertPooler(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = Linear(config.hidden_size, config.hidden_size,
                            weight_attr=_init_attr(config.initializer_range))

    def forward(self, hidden):
        return F.tanh(self.dense(hidden[:, 0]))


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.layers = LayerList([BertLayer(config)
                                 for _ in range(config.num_layers)])
        self.pooler = BertPooler(config)

    @staticmethod
    def _expand_mask(attention_mask, dtype="float32"):
        """[B, T] 1/0 mask → additive [B, 1, 1, T] bias (reference
        transformer.py mask convention)."""
        if attention_mask is None:
            return None
        from ..core.op import apply_op
        import jax.numpy as jnp

        def raw(m):
            m = m.astype(jnp.float32)
            return (1.0 - m[:, None, None, :]) * -1e4

        return apply_op(raw, "bert_mask", (attention_mask,), {})

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        mask = self._expand_mask(attention_mask)
        x = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        for layer in self.layers:
            x = layer(x, attn_mask=mask)
        return x, self.pooler(x)


class BertLMHead(Layer):
    """MLM head: transform + vocab-parallel decoder tied to the word
    embedding (the reference ties weights the same way)."""

    def __init__(self, config: BertConfig, embedding_weight):
        super().__init__()
        self.transform = Linear(config.hidden_size, config.hidden_size,
                                weight_attr=_init_attr(
                                    config.initializer_range))
        self.norm = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.act = getattr(F, config.activation)
        self.decoder_weight = embedding_weight  # tied [V, H]
        self.decoder_bias = self.create_parameter(
            [config.vocab_size], is_bias=True)

    def forward(self, hidden):
        from ..core.op import apply_op

        x = self.norm(self.act(self.transform(hidden)))

        def raw(xv, wv, bv):
            import jax.numpy as jnp
            return jnp.einsum("bth,vh->btv", xv, wv) + bv

        return apply_op(raw, "mlm_logits",
                        (x, self.decoder_weight, self.decoder_bias), {})


class BertForPretraining(Layer):
    """MLM + NSP heads over BertModel (BERT paper pretraining layout)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.cls = BertLMHead(
            config, self.bert.embeddings.word_embeddings.weight)
        self.nsp = Linear(config.hidden_size, 2,
                          weight_attr=_init_attr(config.initializer_range))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        seq, pooled = self.bert(input_ids, token_type_ids, position_ids,
                                attention_mask, task_type_ids=task_type_ids)
        return self.cls(seq), self.nsp(pooled)


class BertPretrainingCriterion(Layer):
    """masked-LM + NSP loss; ignore_index=-100 on MLM labels (reference
    criterion convention)."""

    def __init__(self, vocab_size=None):
        super().__init__()
        self.vocab_size = vocab_size

    def forward(self, prediction_logits, nsp_logits, masked_lm_labels,
                next_sentence_labels=None):
        nll = F.fused_nll_loss(prediction_logits, masked_lm_labels,
                               ignore_index=-100)
        valid = (masked_lm_labels != -100).astype("float32")
        loss = nll.reshape([-1]).sum() / valid.sum().clip(min=1.0)
        if next_sentence_labels is not None:
            nsp = F.cross_entropy(nsp_logits,
                                  next_sentence_labels.reshape([-1]))
            loss = loss + nsp.mean()
        return loss


ErnieConfig = BertConfig
ErnieModel = BertModel
ErnieForPretraining = BertForPretraining


def build_bert(name_or_config="bert-tiny", for_pretraining=True, **overrides):
    cfg = name_or_config if isinstance(name_or_config, BertConfig) else \
        bert_config(name_or_config, **overrides)
    return BertForPretraining(cfg) if for_pretraining else BertModel(cfg)


def build_ernie(name_or_config="ernie-3.0-medium", for_pretraining=True,
                **overrides):
    return build_bert(name_or_config, for_pretraining, **overrides)
