"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built on JAX/XLA/Pallas (see SURVEY.md for the reference map).

The top-level namespace mirrors `import paddle`: tensor creation/math live here,
`nn`, `optimizer`, `amp`, `io`, `vision`, `distributed`… as submodules.
"""
from __future__ import annotations

import jax as _jax

# float64/int64 parity with the reference requires x64 mode; TPU code paths
# should still use fp32/bf16 (float64 on TPU is software-emulated).
_jax.config.update("jax_enable_x64", True)

from .core import (  # noqa: F401,E402
    Tensor, to_tensor,
    no_grad, enable_grad, grad, is_grad_enabled, set_grad_enabled,
    Place, CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace,
    set_device, get_device,
    is_compiled_with_cuda, is_compiled_with_rocm, is_compiled_with_xpu,
    is_compiled_with_tpu, is_compiled_with_distribute,
    bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, set_default_dtype, get_default_dtype,
    seed, get_rng_state, set_rng_state,
)
from .ops import *  # noqa: F401,F403,E402
from .ops import creation as _creation  # noqa: E402

# submodules (imported lazily below to keep `import paddle_tpu` light where
# possible; nn/optimizer pull in the full layer corpus)
from . import nn  # noqa: E402,F401
from . import optimizer  # noqa: E402,F401
from . import amp  # noqa: E402,F401
from . import io  # noqa: E402,F401
from . import metric  # noqa: E402,F401
from . import framework  # noqa: E402,F401
from .framework.io import save, load  # noqa: E402,F401
from . import device  # noqa: E402,F401
from . import autograd  # noqa: E402,F401
from . import distributed  # noqa: E402,F401
from . import incubate  # noqa: E402,F401
from . import vision  # noqa: E402,F401
from . import hapi  # noqa: E402,F401
from . import jit  # noqa: E402,F401
from . import static  # noqa: E402,F401
from . import inference  # noqa: E402,F401
from . import serving  # noqa: E402,F401
from . import profiler  # noqa: E402,F401
from . import distribution  # noqa: E402,F401
from .flags import get_flags, set_flags  # noqa: E402,F401
from . import text  # noqa: E402,F401
from . import audio  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from . import utils  # noqa: E402,F401
from . import fft  # noqa: E402,F401
from . import signal  # noqa: E402,F401
from . import geometric  # noqa: E402,F401
from . import quantization  # noqa: E402,F401
from . import hub  # noqa: E402,F401
from . import dataset  # noqa: E402,F401
from . import reader  # noqa: E402,F401
from . import regularizer  # noqa: E402,F401
from . import sysconfig  # noqa: E402,F401
from . import compat  # noqa: E402,F401
from .batch import batch  # noqa: E402,F401
from . import cost_model  # noqa: E402,F401
from . import tensor  # noqa: E402,F401
# `from .ops import *` already bound the name `linalg` to ops.linalg, which
# makes `from . import linalg` a no-op; import the namespace module explicitly
import importlib as _importlib  # noqa: E402

linalg = _importlib.import_module(".linalg", __name__)
from . import onnx  # noqa: E402,F401
from . import observability  # noqa: E402,F401
from . import version  # noqa: E402,F401


def iinfo(dtype):
    import numpy as _np

    from .core.dtype import convert_dtype as _cd
    return _np.iinfo(_cd(dtype))


def finfo(dtype):
    import ml_dtypes as _mld  # handles bfloat16/fp8 plus all numpy floats

    from .core.dtype import convert_dtype as _cd
    return _mld.finfo(_cd(dtype))
from .nn import ParamAttr  # noqa: E402,F401
from .hapi import Model  # noqa: E402,F401
from . import callbacks  # noqa: E402,F401
from .distributed.parallel import DataParallel  # noqa: E402,F401
from .ops.compat_surface import *  # noqa: E402,F401,F403

# remaining reference top-level aliases (paddle/__init__.py __all__)
bool = bool_  # noqa: A001 — the reference exports `paddle.bool`
dtype = type(float32)
VarBase = Tensor                      # legacy eager tensor alias
LazyGuard = None                      # bound below (needs nn)
CustomPlace = IPUPlace = MLUPlace = NPUPlace = XPUPlace = Place
get_cuda_rng_state = get_rng_state    # device-agnostic RNG state here
set_cuda_rng_state = set_rng_state
commit = "unknown"                    # filled by release tooling upstream
full_version = "0.1.0"


def is_compiled_with_cinn() -> bool:  # noqa: A003
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def get_cudnn_version():
    """None: no cuDNN in a TPU build (reference returns an int or None)."""
    return None


def disable_signal_handler():
    """No-op: the runtime installs no custom signal handlers to disable
    (the reference unhooks its C++ fault handlers here)."""


class LazyGuard:  # noqa: F811
    """Delayed parameter materialization (reference paddle.LazyGuard) —
    maps onto nn.abstract_init: layers built inside the guard carry
    shape/dtype only until a train step or explicit init materializes
    them."""

    def __enter__(self):
        from .nn import abstract_init
        self._cm = abstract_init()
        self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)

from .core.tensor_methods import install_tensor_methods as _itm  # noqa: E402

_itm()
del _itm

__version__ = "0.1.0"

# `paddle.disable_static()/enable_static()` parity: this framework is always
# "dygraph" at the surface (compiled via jit underneath), so these are no-ops
# kept for source compatibility.
_static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def disable_static():
    global _static_mode
    _static_mode = False


def in_dynamic_mode() -> bool:
    return not _static_mode


def is_grad_enabled_():  # legacy alias
    return is_grad_enabled()


def summary(net, input_size=None, dtypes=None):
    from .hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes)


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Forward-pass FLOPs of `net` at `input_size` — measured from XLA's
    own cost analysis of the traced forward (reference hapi/dynamic_flops
    keeps a hand-maintained per-layer registry; the compiler's count
    covers every op, custom ones included, so `custom_ops` is accepted
    for API parity but unnecessary)."""
    import numpy as _np

    import jax as _j
    import jax.numpy as _jnp

    x = _jnp.zeros(tuple(input_size), _jnp.float32)

    def fwd(xv):
        out = net(Tensor(xv, _internal=True))
        return out._value if isinstance(out, Tensor) else out

    try:
        from ._compat import cost_analysis as _cost_analysis
        cost = _cost_analysis(_j.jit(fwd).lower(x).compile())
    except Exception as e:
        import warnings as _w
        _w.warn(f"paddle.flops could not trace the forward at input_size="
                f"{tuple(input_size)} ({type(e).__name__}: {e}); "
                f"returning 0")
        return 0
    total = int(cost.get("flops", 0.0)) if cost else 0
    if print_detail:
        per_param = sum(int(_np.prod(p.shape)) for p in net.parameters())
        print(f"Total Flops: {total}  Total Params: {per_param}")
    return total
