"""paddle.hub parity (python/paddle/hapi/hub.py): load models from a local
hubconf.py (the github/gitee download path needs egress and raises with a
clear message)."""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu_hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source):
    if source not in ("local",):
        raise RuntimeError(
            f"hub source {source!r} needs network egress (not available in "
            "this build); use source='local' with a checked-out repo dir")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def _resolve(repo_dir, model, source):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"model {model!r} not in {repo_dir}/{_HUBCONF}")
    return fn


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    return _resolve(repo_dir, model, source).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    return _resolve(repo_dir, model, source)(**kwargs)
