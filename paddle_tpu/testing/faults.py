"""Fault-injection harness — named fault points threaded through the
crash-critical seams of the stack.

Production code marks a seam with ``fault_point("checkpoint.write",
path=...)``; nothing happens unless a fault is armed for that name, so the
call is a dict lookup on the hot path and free in normal operation.  Tests
(and the chaos smoke lane) arm faults either programmatically::

    with faults.inject("checkpoint.write", mode="raise"):
        saver.save(state, step=2, blocking=True)   # raises FaultInjected

or from the environment for subprocess harnesses::

    PADDLE_TPU_FAULTS="train.step:kill:after=5,fs.upload:raise"

Modes
-----
* ``raise`` — raise :class:`FaultInjected` (default once; ``times=N`` for
  N hits, ``times=None`` forever).  A raise inside a checkpoint write
  leaves the same on-disk state as a crash at that instruction, so the
  crash-matrix tests run in-process.
* ``torn``  — truncate the file passed as ``path=`` to half its size,
  then raise: a torn write, the classic power-loss artifact.
* ``delay`` — sleep ``seconds`` (contention/slow-disk simulation).
* ``kill``  — ``os._exit(exit_code)``: a hard preemption with no cleanup,
  for subprocess tests and the chaos smoke lane.

``after=K`` skips the first K hits (kill-at-step-K); hit counts are
tracked per name for assertions via :func:`hits` (counted whenever the
point is crossed while any fault is armed, matched or not).

Every triggered fault lands in the flight recorder (``kind="fault"``) so
a chaos run's crash dump shows what was injected where.

Fault points in the tree (see docs/robustness.md for the catalogue):
``checkpoint.write``, ``checkpoint.manifest``, ``checkpoint.commit``,
``checkpoint.promote``, ``checkpoint.upload``,
``checkpoint.upload_commit``, ``fs.upload``, ``fs.download``,
``serving.scheduler``, ``train.step``, the elastic-restore path
(ISSUE 6) — ``restore.read`` (per-leaf checkpoint read, before CRC),
``restore.relayout`` (before a leaf/state is laid out on the target
mesh), ``restore.rng`` (RNG-key restore) — and the self-healing serving
path (ISSUE 9): ``serving.prefill`` / ``serving.decode`` (before each
batched dispatch; a crash there loses zero-token vs. streamed requests
respectively), ``serving.stream`` (per emitted token — ``after=K`` lets
K tokens through, then the death interrupts a live stream),
``serving.rebuild`` (the supervisor's engine-rebuild step),
``gateway.dispatch`` (the gateway dispatcher loop, whose death must
degrade /healthz), and the fleet-elasticity path (ISSUE 15):
``scale.up_build`` (before the autoscaler's factory builds a new
replica — a crash there fails that scale-up, which must be retried),
``scale.down_drain`` (before a scale-down's drain begins — the replica
must still leave only after draining empty) and ``autoscaler.tick``
(the control loop body, whose crash must be absorbed, never ending
scaling silently).  The rolling-upgrade path (ISSUE 20):
``rollout.build`` (before the rollout controller builds a replica at
the target revision — a crash fails that build, which is retried, or
rolls the canary back if the retries run out before anything routed
in), ``rollout.canary_gate`` (inside the canary-judgment loop — a
crashed evaluation is absorbed and the gate re-judged, never skipped)
and ``rollout.drain_old`` (before an incumbent's drain begins — the
old replica must still leave only once empty, exactly like a
scale-down).  A fault anywhere along the restore path must leave
BOTH the checkpoint dir and the running train state untouched —
asserted by the elastic crash matrix in tests/test_elastic.py.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time

__all__ = ["FaultInjected", "fault_point", "inject", "arm", "disarm",
           "reset", "hits", "armed", "CATALOGUE"]

# The operator-facing seam index (docs/robustness.md catalogue).  Every
# literal ``fault_point("...")`` in the tree must be listed here AND be
# exercised by the crash-matrix tests — both are enforced statically by
# tools/tpu_lint.py (rules faults.uncatalogued-seam /
# faults.uncovered-seam), so a new seam cannot silently ship untested.
# Dynamic seams (``fault_point(name)`` forwarding fs.upload/fs.download)
# are accounted for by their entry here.
CATALOGUE = (
    "checkpoint.write", "checkpoint.manifest", "checkpoint.commit",
    "checkpoint.promote", "checkpoint.upload", "checkpoint.upload_commit",
    "fs.upload", "fs.download",
    "restore.read", "restore.relayout", "restore.rng",
    "serving.scheduler", "serving.prefill", "serving.decode",
    "serving.stream", "serving.rebuild", "gateway.dispatch",
    "scale.up_build", "scale.down_drain", "autoscaler.tick",
    "rollout.build", "rollout.canary_gate", "rollout.drain_old",
    "train.step",
)


class FaultInjected(RuntimeError):
    """Raised by an armed ``raise``/``torn`` fault point."""

    def __init__(self, name: str, mode: str = "raise"):
        super().__init__(f"injected fault at {name!r} (mode={mode})")
        self.point = name
        self.mode = mode


class _Fault:
    __slots__ = ("name", "mode", "times", "after", "seconds", "exit_code",
                 "exc", "triggered")

    def __init__(self, name, mode="raise", times=1, after=0, seconds=0.05,
                 exit_code=43, exc=None):
        if mode not in ("raise", "torn", "delay", "kill"):
            raise ValueError(f"unknown fault mode {mode!r}")
        self.name = name
        self.mode = mode
        self.times = None if times is None else int(times)
        self.after = int(after)
        self.seconds = float(seconds)
        self.exit_code = int(exit_code)
        self.exc = exc
        self.triggered = 0


_lock = threading.Lock()
_faults: dict[str, _Fault] = {}
_hits: dict[str, int] = {}


def armed() -> bool:
    return bool(_faults)


def arm(name: str, mode: str = "raise", **kw) -> _Fault:
    """Arm one fault; replaces any previous fault on the same name."""
    f = _Fault(name, mode, **kw)
    with _lock:
        _faults[name] = f
    return f


def disarm(name: str):
    with _lock:
        _faults.pop(name, None)


def reset():
    """Disarm everything and zero the hit counters (test teardown)."""
    with _lock:
        _faults.clear()
        _hits.clear()


def hits(name: str) -> int:
    """How many times `name` was crossed while any fault was armed."""
    with _lock:
        return _hits.get(name, 0)


@contextlib.contextmanager
def inject(name: str, mode: str = "raise", **kw):
    """Arm a fault for the scope: ``with inject("fs.upload", times=1): ...``"""
    f = arm(name, mode, **kw)
    try:
        yield f
    finally:
        disarm(name)


def _torn(path: str | None):
    if path and os.path.isfile(path):
        size = os.path.getsize(path)
        with open(path, "r+b") as fh:
            fh.truncate(max(1, size // 2))


def fault_point(name: str, path: str | None = None, **ctx):
    """Crash-critical seam marker.  A dict lookup when nothing is armed."""
    if not _faults:
        return
    with _lock:
        _hits[name] = _hits.get(name, 0) + 1
        f = _faults.get(name)
        if f is None:
            return
        f.triggered += 1
        if f.triggered <= f.after:
            return
        if f.times is not None and f.triggered - f.after > f.times:
            return
        mode = f.mode
    from ..observability import flight
    flight.record("fault", name, mode=mode, hit=f.triggered,
                  **{k: v for k, v in ctx.items()
                     if isinstance(v, (str, int, float, bool))})
    if mode == "delay":
        time.sleep(f.seconds)
        return
    if mode == "kill":
        os._exit(f.exit_code)
    if mode == "torn":
        _torn(path)
    if f.exc is not None:
        raise f.exc
    raise FaultInjected(name, mode)


def _load_env(spec: str | None = None):
    """Arm faults from ``PADDLE_TPU_FAULTS``: comma-separated entries of
    ``name[:mode[:key=val]...]`` — e.g. ``train.step:kill:after=5``."""
    spec = spec if spec is not None else os.environ.get(
        "PADDLE_TPU_FAULTS", "")
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        parts = entry.split(":")
        name, mode = parts[0], (parts[1] if len(parts) > 1 else "raise")
        kw: dict = {}
        for field in parts[2:]:
            k, _, v = field.partition("=")
            kw[k] = None if v == "none" else (
                float(v) if k == "seconds" else int(v))
        arm(name, mode, **kw)


_load_env()
