"""paddle_tpu.testing — fault injection and chaos-test helpers.

The production modules call :func:`paddle_tpu.testing.faults.fault_point`
at their crash-critical seams (checkpoint writes, remote uploads, the
serving scheduler, the train loop); tests and the chaos smoke lane arm
faults there to prove kill-and-resume is a working path, not a hope.
"""
from . import faults  # noqa: F401
from .faults import FaultInjected, fault_point, inject  # noqa: F401

__all__ = ["faults", "FaultInjected", "fault_point", "inject"]
