"""VOC2012 segmentation dataset — parity with
python/paddle/vision/datasets/voc2012.py (parses the VOCtrainval tar:
JPEGImages/*.jpg + SegmentationClass/*.png keyed by the ImageSets/
Segmentation/{train,val,trainval}.txt lists), local archive only.

Images decode through Pillow when available; without it the dataset still
indexes the archive and raises a clear error on access.
"""
from __future__ import annotations

import io
import os
import tarfile
from typing import Optional

import numpy as np

from ...io.dataset import Dataset

__all__ = ["VOC2012"]

_SETS = {"train": "train.txt", "valid": "val.txt", "test": "trainval.txt"}


class VOC2012(Dataset):
    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform=None, download: bool = False, backend=None):
        if data_file is None:
            raise ValueError(
                "VOC2012: this build has no network egress; pass data_file= "
                "pointing at the locally-downloaded VOCtrainval tar")
        if not os.path.exists(data_file):
            raise FileNotFoundError(data_file)
        if mode not in _SETS:
            raise ValueError(f"mode must be one of {sorted(_SETS)}")
        if backend not in (None, "numpy"):
            # decoding always yields ndarrays; reject backends whose return
            # type we would silently betray ('pil' images, 'cv2') loudly
            raise ValueError(f"unsupported backend {backend!r}; this build "
                             "returns numpy arrays (use None or 'numpy')")
        self.transform = transform
        self._tar_path = data_file
        # one TarFile per (pid) — forked DataLoader workers must not share
        # the parent's file offset (concurrent extractfile would interleave)
        self._tars: dict = {}
        tar = self._tar()
        try:
            names = {m.name: m for m in tar.getmembers()}
            list_name = next(
                (n for n in names
                 if n.endswith(f"ImageSets/Segmentation/{_SETS[mode]}")),
                None)
            if list_name is None:
                raise ValueError(
                    f"archive has no ImageSets/Segmentation/{_SETS[mode]}")
            ids = tar.extractfile(names[list_name]).read().decode().split()
        except Exception:
            self.close()
            raise
        root = list_name.split("ImageSets/")[0]
        self._pairs = []
        for i in ids:
            img = f"{root}JPEGImages/{i}.jpg"
            seg = f"{root}SegmentationClass/{i}.png"
            if img in names and seg in names:
                self._pairs.append((names[img], names[seg]))

    def _tar(self) -> tarfile.TarFile:
        pid = os.getpid()
        tar = self._tars.get(pid)
        if tar is None:
            tar = tarfile.open(self._tar_path, "r:*")
            self._tars[pid] = tar
        return tar

    def close(self) -> None:
        for tar in self._tars.values():
            try:
                tar.close()
            except OSError:
                pass
        self._tars.clear()

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass

    def _decode(self, member) -> np.ndarray:
        data = self._tar().extractfile(member).read()
        try:
            from PIL import Image
        except ImportError as e:  # pragma: no cover - PIL present here
            raise RuntimeError(
                "VOC2012 image decoding needs Pillow") from e
        return np.asarray(Image.open(io.BytesIO(data)))

    def __getitem__(self, idx):
        img_m, seg_m = self._pairs[idx]
        image = self._decode(img_m)
        label = self._decode(seg_m)
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self._pairs)
