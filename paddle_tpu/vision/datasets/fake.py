"""FakeData — synthetic image classification dataset for tests and smoke
training (fills the role of the reference's fake readers in tests)."""
from __future__ import annotations

import numpy as np

from ...io.dataset import Dataset


class FakeData(Dataset):
    def __init__(self, size=100, image_shape=(3, 224, 224), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        img = rng.standard_normal(self.image_shape).astype("float32")
        label = np.array([int(rng.integers(0, self.num_classes))], "int64")
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.size
