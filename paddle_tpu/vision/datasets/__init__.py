"""paddle.vision.datasets parity (python/paddle/vision/datasets/).

No-egress build: datasets load from LOCAL files (pass `image_path`/
`data_file`); the download=True default of the reference raises with a clear
message instead of fetching.  `FakeData` provides synthetic samples for
tests/smoke-training (the reference's fake reader pattern).
"""
from .folder import DatasetFolder, ImageFolder  # noqa: F401
from .mnist import MNIST, FashionMNIST  # noqa: F401
from .cifar import Cifar10, Cifar100  # noqa: F401
from .fake import FakeData  # noqa: F401
from .flowers import Flowers  # noqa: F401
from .voc2012 import VOC2012  # noqa: F401
