"""Directory-tree image datasets (reference:
python/paddle/vision/datasets/folder.py:65 `DatasetFolder`, :297
`ImageFolder`) — the entry point of every reference CV recipe that trains
on a local directory of images.

Layout contracts:

``DatasetFolder``: ``root/<class_x>/**/*.ext`` — one sub-directory per
class, classes sorted by name to form `class_to_idx`; samples are
``(path, class_index)`` walked in sorted order.

``ImageFolder``: every valid file under ``root`` (recursively, sorted), no
labels — ``__getitem__`` returns ``[sample]`` like the reference.
"""
from __future__ import annotations

import os

from ...io import Dataset

__all__ = ["DatasetFolder", "ImageFolder", "has_valid_extension",
           "make_dataset", "IMG_EXTENSIONS", "default_loader", "pil_loader"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def has_valid_extension(filename, extensions):
    """True when `filename` ends with one of `extensions` (case-folded)."""
    assert isinstance(extensions, (list, tuple)), \
        "`extensions` must be list or tuple."
    lowered = filename.lower()
    return any(lowered.endswith(str(ext).lower()) for ext in extensions)


def _walk_files(base):
    """Every file under `base` in the deterministic (sorted dirs, sorted
    names, symlinks followed) order the folder datasets contract fixes."""
    for root, _, fnames in sorted(os.walk(base, followlinks=True)):
        for fname in sorted(fnames):
            yield os.path.join(root, fname)


def make_dataset(dir, class_to_idx, extensions, is_valid_file=None):  # noqa: A002
    """Walk `dir/<class>/**` collecting (path, class_index) pairs in sorted
    order (folder.py make_dataset contract).  `extensions`, when given,
    replaces `is_valid_file` with the extension predicate."""
    base = os.path.expanduser(dir)
    if extensions is not None:
        def is_valid_file(path):  # noqa: F811
            return has_valid_extension(path, extensions)
    samples = []
    for target, idx in sorted(class_to_idx.items()):
        class_dir = os.path.join(base, target)
        if not os.path.isdir(class_dir):
            continue
        samples.extend((path, idx) for path in _walk_files(class_dir)
                       if is_valid_file(path))
    return samples


def pil_loader(path):
    from PIL import Image
    with open(path, "rb") as f:
        return Image.open(f).convert("RGB")


def cv2_loader(path):
    import cv2
    return cv2.cvtColor(cv2.imread(path), cv2.COLOR_BGR2RGB)


def default_loader(path):
    from .. import get_image_backend  # deferred: vision imports datasets
    return cv2_loader(path) if get_image_backend() == "cv2" \
        else pil_loader(path)


class DatasetFolder(Dataset):
    """folder.py:65 parity: one class per sub-directory of `root`.

    Attributes: classes, class_to_idx, samples [(path, idx)], targets.
    """

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        # the documented contract: extensions and is_valid_file are
        # mutually exclusive; the default extension list applies only when
        # no predicate is given (otherwise the predicate would be silently
        # shadowed by the extension filter inside make_dataset)
        if extensions is not None and is_valid_file is not None:
            raise ValueError(
                "Both `extensions` and `is_valid_file` should not be "
                "passed.")
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        classes, class_to_idx = self._find_classes(root)
        samples = make_dataset(root, class_to_idx, extensions, is_valid_file)
        if len(samples) == 0:
            raise RuntimeError(
                f"Found 0 directories in subfolders of: {root}\n"
                "Supported extensions are: "
                + ",".join(extensions or ()))
        self.loader = default_loader if loader is None else loader
        self.extensions = extensions
        self.classes = classes
        self.class_to_idx = class_to_idx
        self.samples = samples
        self.targets = [s[1] for s in samples]

    @staticmethod
    def _find_classes(dir):  # noqa: A002
        classes = sorted(d.name for d in os.scandir(dir) if d.is_dir())
        return classes, {c: i for i, c in enumerate(classes)}

    def __getitem__(self, index):
        path, target = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """folder.py:297 parity: every valid file under `root`, unlabeled;
    items are returned as a one-element list like the reference."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        if extensions is not None and is_valid_file is not None:
            raise ValueError(
                "Both `extensions` and `is_valid_file` should not be "
                "passed.")
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        if is_valid_file is None:
            def is_valid_file(path):
                return has_valid_extension(path, extensions)
        samples = [path for path in _walk_files(os.path.expanduser(root))
                   if is_valid_file(path)]
        if len(samples) == 0:
            raise RuntimeError(
                f"Found 0 files in subfolders of: {root}\n"
                "Supported extensions are: "
                + ",".join(extensions or ()))
        self.loader = default_loader if loader is None else loader
        self.extensions = extensions
        self.samples = samples
        self.transform = transform

    def __getitem__(self, index):
        sample = self.loader(self.samples[index])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
