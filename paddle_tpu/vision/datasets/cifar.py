"""Cifar10/100 — parity with python/paddle/vision/datasets/cifar.py
(python-pickle batch format), local files only."""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from ...io.dataset import Dataset


class Cifar10(Dataset):
    _LABEL_KEY = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file is None:
            raise ValueError(
                "cifar: this build has no network egress; pass the local "
                "cifar tar.gz path as data_file")
        if not os.path.exists(data_file):
            raise FileNotFoundError(data_file)
        self.mode = mode
        self.transform = transform
        self.data = []
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                name = os.path.basename(member.name)
                if (mode == "train" and ("data_batch" in name or
                                         name == "train")) or \
                        (mode == "test" and ("test_batch" in name or
                                             name == "test")):
                    batch = pickle.load(tf.extractfile(member),
                                        encoding="bytes")
                    images = batch[b"data"].reshape(-1, 3, 32, 32)
                    labels = batch.get(self._LABEL_KEY,
                                       batch.get(b"fine_labels"))
                    for img, lbl in zip(images, labels):
                        self.data.append((img, lbl))

    def __getitem__(self, idx):
        img, label = self.data[idx]
        img = img.transpose(1, 2, 0)  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([label], dtype="int64")

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    _LABEL_KEY = b"fine_labels"
