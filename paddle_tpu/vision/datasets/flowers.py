"""Flowers — parity with python/paddle/vision/datasets/flowers.py, local
files only.  The reference reads scipy .mat label/setid files; this no-scipy
build accepts .npy/.npz equivalents (labels: [N] int array, 1-based like the
original; setid: npz with 'trnid'/'valid'/'tstid' or a plain index array)."""
from __future__ import annotations

import io
import os
import tarfile

import numpy as np

from ...io.dataset import Dataset

_MODE_KEY = {"train": "trnid", "valid": "valid", "test": "tstid"}


class Flowers(Dataset):
    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False, backend=None):
        if data_file is None:
            raise ValueError(
                "flowers: this build has no network egress; pass local "
                "data_file/label_file/setid_file paths")
        for p in (data_file, label_file, setid_file):
            if p is not None and not os.path.exists(p):
                raise FileNotFoundError(p)
        self.transform = transform
        self.mode = mode
        self._tar = tarfile.open(data_file)
        names = sorted(m.name for m in self._tar.getmembers() if m.isfile())
        self.labels = np.load(label_file) if label_file else None

        if setid_file is not None:
            setid = np.load(setid_file)
            if hasattr(setid, "files"):  # npz with per-split keys
                idxs = setid[_MODE_KEY[mode]]
            else:
                idxs = setid
            # reference setids are 1-based image numbers
            self._indices = [int(i) - 1 for i in np.ravel(idxs)]
        else:
            self._indices = list(range(len(names)))
        self._names = names

    def __getitem__(self, idx):
        i = self._indices[idx]
        data = self._tar.extractfile(self._names[i]).read()
        try:
            from PIL import Image
            img = np.asarray(Image.open(io.BytesIO(data)))
        except ImportError as e:  # pragma: no cover
            raise RuntimeError("Flowers requires PIL for jpeg decode") from e
        if self.transform is not None:
            img = self.transform(img)
        label = int(self.labels[i]) if self.labels is not None else -1
        return img, np.array([label], "int64")

    def __len__(self):
        return len(self._indices)
