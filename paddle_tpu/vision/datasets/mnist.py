"""MNIST/FashionMNIST — parity with python/paddle/vision/datasets/mnist.py
(idx-ubyte file parsing), local files only."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io.dataset import Dataset


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


class MNIST(Dataset):
    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download and (image_path is None or label_path is None):
            raise ValueError(
                f"{self.NAME}: this build has no network egress; pass local "
                "image_path/label_path (idx-ubyte, optionally .gz)")
        if image_path is None or label_path is None:
            raise ValueError("image_path and label_path are required")
        if not os.path.exists(image_path) or not os.path.exists(label_path):
            raise FileNotFoundError(f"{image_path} / {label_path}")
        self.mode = mode
        self.transform = transform
        self.images = _read_idx(image_path)
        self.labels = _read_idx(label_path).astype("int64")

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([label], dtype="int64")

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"
