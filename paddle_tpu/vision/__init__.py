"""paddle.vision parity (SURVEY §2.3: vision/models model zoo, transforms,
ops.py detection ops, datasets)."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from .models import *  # noqa: F401,F403
from .datasets import *  # noqa: F401,F403
from .transforms import *  # noqa: F401,F403
# the star imports above leak inner-module attributes (e.g. the package's
# own `transforms` attr = transforms/transforms.py) over the package
# bindings; `from . import X` would just re-read the shadowed attr, so
# restore from sys.modules explicitly
import sys as _sys  # noqa: E402

datasets = _sys.modules[__name__ + ".datasets"]
models = _sys.modules[__name__ + ".models"]
transforms = _sys.modules[__name__ + ".transforms"]


def set_image_backend(backend):
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unknown image backend {backend!r}")
    global _image_backend
    _image_backend = backend


def get_image_backend():
    return _image_backend


_image_backend = "pil"


def image_load(path, backend=None):
    """vision/image.py image_load: decode via the configured backend."""
    from .datasets.folder import default_loader
    try:
        return default_loader(path)
    except Exception:
        from ..dataset.image import load_image
        return load_image(path)
