"""paddle.vision parity (SURVEY §2.3: vision/models model zoo, transforms,
ops.py detection ops, datasets)."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from .models import *  # noqa: F401,F403


def set_image_backend(backend):
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unknown image backend {backend!r}")
    global _image_backend
    _image_backend = backend


def get_image_backend():
    return _image_backend


_image_backend = "pil"
