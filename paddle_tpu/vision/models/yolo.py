"""YOLOv3-family detector — the PP-YOLOE-class conv detection config from
the BASELINE matrix (reference recipes live in PaddleDetection; the in-repo
kernel surface is vision/ops.py yolo_box + the darknet-style backbones).

Compact TPU-first build: CSP-style backbone (all dense convs — MXU), an
upsample FPN neck, per-scale heads emitting the reference yolo_box layout
[N, A*(5+C), H, W], decode through ops.yolo_box + ops.nms, and the classic
YOLOv3 multi-part loss (obj BCE + cls BCE + CIoU-free box regression on
assigned anchors) for training.
"""
from __future__ import annotations

import numpy as np

from ... import nn
from ...ops.manipulation import concat
from .. import ops as vops

_DEFAULT_ANCHORS = [[10, 13, 16, 30, 33, 23],
                    [30, 61, 62, 45, 59, 119],
                    [116, 90, 156, 198, 373, 326]]


class ConvBNLayer(nn.Layer):
    def __init__(self, cin, cout, k=3, stride=1, act="leaky_relu"):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=(k - 1) // 2, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = nn.LeakyReLU(0.1) if act == "leaky_relu" else nn.Swish()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class CSPBlock(nn.Layer):
    """Cross-stage-partial residual stage (PP-YOLOE backbone shape)."""

    def __init__(self, cin, cout, n_blocks, stride=2):
        super().__init__()
        self.down = ConvBNLayer(cin, cout, 3, stride=stride)
        half = cout // 2
        self.split1 = ConvBNLayer(cout, half, 1)
        self.split2 = ConvBNLayer(cout, half, 1)
        self.blocks = nn.LayerList([
            nn.Sequential(ConvBNLayer(half, half, 1),
                          ConvBNLayer(half, half, 3))
            for _ in range(n_blocks)])
        self.fuse = ConvBNLayer(cout, cout, 1)

    def forward(self, x):
        x = self.down(x)
        a = self.split1(x)
        b = self.split2(x)
        for blk in self.blocks:
            b = b + blk(b)
        return self.fuse(concat([a, b], axis=1))


class CSPBackbone(nn.Layer):
    """Returns C3, C4, C5 feature maps (strides 8/16/32)."""

    def __init__(self, width=32, depths=(1, 2, 2, 1)):
        super().__init__()
        w = width
        self.stem = ConvBNLayer(3, w, 3, stride=2)
        self.stage1 = CSPBlock(w, w * 2, depths[0])       # /4
        self.stage2 = CSPBlock(w * 2, w * 4, depths[1])   # /8  -> C3
        self.stage3 = CSPBlock(w * 4, w * 8, depths[2])   # /16 -> C4
        self.stage4 = CSPBlock(w * 8, w * 16, depths[3])  # /32 -> C5
        self.out_channels = (w * 4, w * 8, w * 16)

    def forward(self, x):
        x = self.stem(x)
        x = self.stage1(x)
        c3 = self.stage2(x)
        c4 = self.stage3(c3)
        c5 = self.stage4(c4)
        return c3, c4, c5


class FPNNeck(nn.Layer):
    """Top-down upsample fusion producing one feature per scale."""

    def __init__(self, in_channels, out_channel=128):
        super().__init__()
        c3, c4, c5 = in_channels
        self.lat5 = ConvBNLayer(c5, out_channel, 1)
        self.lat4 = ConvBNLayer(c4, out_channel, 1)
        self.lat3 = ConvBNLayer(c3, out_channel, 1)
        self.up = nn.UpsamplingNearest2D(scale_factor=2)
        self.out5 = ConvBNLayer(out_channel, out_channel, 3)
        self.out4 = ConvBNLayer(out_channel, out_channel, 3)
        self.out3 = ConvBNLayer(out_channel, out_channel, 3)

    def forward(self, feats):
        c3, c4, c5 = feats
        p5 = self.lat5(c5)
        p4 = self.lat4(c4) + self.up(p5)
        p3 = self.lat3(c3) + self.up(p4)
        return self.out3(p3), self.out4(p4), self.out5(p5)


class YOLOHead(nn.Layer):
    def __init__(self, in_channel, num_anchors, num_classes):
        super().__init__()
        self.pred = nn.Conv2D(in_channel, num_anchors * (5 + num_classes), 1)

    def forward(self, x):
        return self.pred(x)


class YOLOv3(nn.Layer):
    """Detector: train mode returns raw per-scale heads; `decode` produces
    boxes/scores via ops.yolo_box; `predict` adds per-image NMS."""

    def __init__(self, num_classes=80, anchors=None, width=32,
                 neck_channel=128, conf_thresh=0.05, nms_thresh=0.45):
        super().__init__()
        self.num_classes = num_classes
        self.anchors = anchors or _DEFAULT_ANCHORS
        self.strides = (8, 16, 32)
        self.conf_thresh = conf_thresh
        self.nms_thresh = nms_thresh
        self.backbone = CSPBackbone(width=width)
        self.neck = FPNNeck(self.backbone.out_channels, neck_channel)
        na = len(self.anchors[0]) // 2
        self.heads = nn.LayerList([
            YOLOHead(neck_channel, na, num_classes) for _ in range(3)])

    def forward(self, x):
        feats = self.neck(self.backbone(x))
        return [head(f) for head, f in zip(self.heads, feats)]

    def decode(self, heads, img_size):
        """heads → (boxes [N, M, 4], scores [N, M, C]) across scales."""
        boxes, scores = [], []
        for head, anchors, stride in zip(heads, self.anchors, self.strides):
            b, s = vops.yolo_box(head, img_size, anchors, self.num_classes,
                                 self.conf_thresh, stride)
            boxes.append(b)
            scores.append(s)
        return concat(boxes, axis=1), concat(scores, axis=1)

    def predict(self, x, img_size, top_k=100):
        """Returns per-image arrays of (x0, y0, x1, y1, score, class) rows."""
        import paddle_tpu as paddle

        was_training = self.training
        self.eval()
        try:
            heads = self.forward(x)
            boxes, scores = self.decode(heads, img_size)
            boxes_np = boxes.numpy()
            scores_np = scores.numpy()
        finally:
            if was_training:
                self.train()
        results = []
        for i in range(boxes_np.shape[0]):
            b_np = boxes_np[i]
            cls_score = scores_np[i].max(axis=-1)
            cls_id = scores_np[i].argmax(axis=-1)
            idxs = np.nonzero(cls_score > self.conf_thresh)[0]
            if idxs.size == 0:
                results.append(np.zeros((0, 6), "float32"))
                continue
            kept = vops.nms(
                paddle.to_tensor(b_np[idxs]), self.nms_thresh,
                scores=paddle.to_tensor(cls_score[idxs].astype("float32")),
                category_idxs=paddle.to_tensor(cls_id[idxs].astype("int64")),
                categories=list(range(self.num_classes)),
                top_k=top_k).numpy()
            rows = np.concatenate([
                b_np[idxs][kept],
                cls_score[idxs][kept, None].astype("float32"),
                cls_id[idxs][kept, None].astype("float32")], axis=1)
            results.append(rows.astype("float32"))
        return results


class YOLOv3Loss(nn.Layer):
    """Classic YOLOv3 loss over raw heads with grid-assigned targets.

    Targets: list per image of (box_xyxy_pixels [M,4], class_id [M]).  The
    assignment (best anchor by wh-IoU at the center cell) runs in numpy on
    host — it is data-dependent bookkeeping, not device math (the reference
    does the same inside yolov3_loss_op's CPU path).
    """

    def __init__(self, model: YOLOv3):
        super().__init__()
        self.model = model

    def build_targets(self, heads, gt_list):
        model = self.model
        na = len(model.anchors[0]) // 2
        targets = []
        for head, anchors, stride in zip(heads, model.anchors, model.strides):
            n, _, h, w = head.shape
            anc = np.asarray(anchors, "float32").reshape(-1, 2)
            tobj = np.zeros((n, na, h, w), "float32")
            tbox = np.zeros((n, na, h, w, 4), "float32")
            tcls = np.zeros((n, na, h, w), "int64")
            for i, (boxes, classes) in enumerate(gt_list):
                for bx, cl in zip(np.asarray(boxes, "float32"),
                                  np.asarray(classes)):
                    cx = (bx[0] + bx[2]) / 2
                    cy = (bx[1] + bx[3]) / 2
                    bw = max(bx[2] - bx[0], 1e-3)
                    bh = max(bx[3] - bx[1], 1e-3)
                    gx, gy = int(cx / stride), int(cy / stride)
                    if not (0 <= gx < w and 0 <= gy < h):
                        continue
                    inter = np.minimum(anc[:, 0], bw) * \
                        np.minimum(anc[:, 1], bh)
                    union = anc[:, 0] * anc[:, 1] + bw * bh - inter
                    a = int((inter / union).argmax())
                    tobj[i, a, gy, gx] = 1.0
                    tbox[i, a, gy, gx] = [cx / stride - gx, cy / stride - gy,
                                          np.log(bw / anc[a, 0]),
                                          np.log(bh / anc[a, 1])]
                    tcls[i, a, gy, gx] = int(cl)
            targets.append((tobj, tbox, tcls))
        return targets

    def forward(self, heads, gt_list):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F

        targets = self.build_targets(heads, gt_list)
        total = None
        nc = self.model.num_classes
        na = len(self.model.anchors[0]) // 2
        for head, (tobj, tbox, tcls) in zip(heads, targets):
            n, _, h, w = head.shape
            p = head.reshape([n, na, 5 + nc, h, w])
            pxy = p[:, :, 0:2]
            pwh = p[:, :, 2:4]
            pobj = p[:, :, 4]
            pcls = p[:, :, 5:]
            obj_t = paddle.to_tensor(tobj)
            box_t = paddle.to_tensor(tbox)
            cls_t = paddle.to_tensor(tcls)

            loss_obj = F.binary_cross_entropy_with_logits(
                pobj, obj_t, reduction="mean")
            mask = obj_t.unsqueeze(2)
            # xy via sigmoid-BCE against cell offsets, wh via L2 on log
            # space; tbox [n,na,h,w,4] → [n,na,4,h,w] to match the head
            box_nchw = box_t.transpose([0, 1, 4, 2, 3])
            xy_t = box_nchw[:, :, 0:2]
            wh_t = box_nchw[:, :, 2:4]
            loss_xy = (F.binary_cross_entropy_with_logits(
                pxy, xy_t, reduction="none") * mask).sum() / \
                mask.sum().clip(min=1.0) / 2
            loss_wh = (((pwh - wh_t) ** 2) * mask).sum() / \
                mask.sum().clip(min=1.0) / 2
            cls_oh = F.one_hot(cls_t, nc).transpose([0, 1, 4, 2, 3])
            loss_cls = (F.binary_cross_entropy_with_logits(
                pcls, cls_oh.astype("float32"), reduction="none") *
                mask).sum() / mask.sum().clip(min=1.0) / nc
            part = loss_obj + loss_xy + loss_wh + loss_cls
            total = part if total is None else total + part
        return total


def yolov3(num_classes=80, pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load a local "
                         "state_dict instead")
    return YOLOv3(num_classes=num_classes, **kwargs)


def yolo_head_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                   ignore_thresh, downsample_ratio, gt_score=None,
                   use_label_smooth=True, scale_x_y=1.0):
    """Functional single-head YOLOv3 loss with the yolo_loss OP contract
    (vision/ops.py yolo_loss; kernel yolov3_loss_op): x [N, A*(5+C), H, W],
    gt_box [N, B, 4] normalized (cx, cy, w, h), anchors a flat pixel
    list, anchor_mask the indices this head owns.  Returns loss [N].
    Same math as YOLOv3Loss above, head-local."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    xv = x if hasattr(x, "shape") else paddle.to_tensor(x)
    n, _, h, w = xv.shape
    stride = downsample_ratio
    in_h, in_w = h * stride, w * stride
    anc_all = np.asarray(anchors, "float32").reshape(-1, 2)
    anc = anc_all[np.asarray(anchor_mask, int)]
    na = anc.shape[0]
    gb = np.asarray(gt_box.numpy() if hasattr(gt_box, "numpy") else gt_box,
                    "float32")
    gl = np.asarray(gt_label.numpy() if hasattr(gt_label, "numpy")
                    else gt_label)
    tobj = np.zeros((n, na, h, w), "float32")
    tbox = np.zeros((n, na, h, w, 4), "float32")
    tcls = np.zeros((n, na, h, w), "int64")
    for i in range(n):
        for bx, cl in zip(gb[i], gl[i]):
            cx, cy, bw, bh = bx
            if bw <= 0 or bh <= 0:
                continue
            bw_p, bh_p = bw * in_w, bh * in_h
            gx, gy = int(cx * w), int(cy * h)
            if not (0 <= gx < w and 0 <= gy < h):
                continue
            inter = np.minimum(anc_all[:, 0], bw_p) * \
                np.minimum(anc_all[:, 1], bh_p)
            union = anc_all[:, 0] * anc_all[:, 1] + bw_p * bh_p - inter
            best = int((inter / union).argmax())
            if best not in list(anchor_mask):
                continue
            a = list(anchor_mask).index(best)
            tobj[i, a, gy, gx] = 1.0
            tbox[i, a, gy, gx] = [cx * w - gx, cy * h - gy,
                                  np.log(max(bw_p, 1e-3) / anc[a, 0]),
                                  np.log(max(bh_p, 1e-3) / anc[a, 1])]
            tcls[i, a, gy, gx] = int(cl)
    p = xv.reshape([n, na, 5 + class_num, h, w])
    pxy, pwh = p[:, :, 0:2], p[:, :, 2:4]
    pobj, pcls = p[:, :, 4], p[:, :, 5:]
    # ignore_thresh (yolov3_loss_op contract): decode the predictions and
    # EXCLUDE unassigned anchors whose best IoU with any GT exceeds the
    # threshold from the no-object loss.  Host bookkeeping on detached
    # values, like the target assignment above.
    pv = np.asarray(p.numpy() if hasattr(p, "numpy") else p)
    sig = 1.0 / (1.0 + np.exp(-pv[:, :, 0:2]))
    gyx = np.stack(np.meshgrid(np.arange(h), np.arange(w),
                               indexing="ij"))          # [2, h, w]
    pcx = (sig[:, :, 0] + gyx[1][None, None]) / w
    pcy = (sig[:, :, 1] + gyx[0][None, None]) / h
    pw_ = np.exp(np.clip(pv[:, :, 2], -10, 10)) \
        * anc[:, 0][None, :, None, None] / in_w
    ph_ = np.exp(np.clip(pv[:, :, 3], -10, 10)) \
        * anc[:, 1][None, :, None, None] / in_h
    ignore = np.zeros((n, na, h, w), "float32")
    for i in range(n):
        valid = [(bx, ) for bx in gb[i] if bx[2] > 0 and bx[3] > 0]
        if not valid:
            continue
        gtb = np.asarray([bx for (bx,) in valid], "float32")  # [M, 4]
        px1 = pcx[i] - pw_[i] / 2
        py1 = pcy[i] - ph_[i] / 2
        px2 = pcx[i] + pw_[i] / 2
        py2 = pcy[i] + ph_[i] / 2
        gx1 = gtb[:, 0] - gtb[:, 2] / 2
        gy1 = gtb[:, 1] - gtb[:, 3] / 2
        gx2 = gtb[:, 0] + gtb[:, 2] / 2
        gy2 = gtb[:, 1] + gtb[:, 3] / 2
        best = np.zeros((na, h, w), "float32")
        for m in range(gtb.shape[0]):
            iw = np.clip(np.minimum(px2, gx2[m])
                         - np.maximum(px1, gx1[m]), 0, None)
            ih = np.clip(np.minimum(py2, gy2[m])
                         - np.maximum(py1, gy1[m]), 0, None)
            inter = iw * ih
            union = pw_[i] * ph_[i] + gtb[m, 2] * gtb[m, 3] - inter
            best = np.maximum(best, inter / np.maximum(union, 1e-10))
        ignore[i] = (best > ignore_thresh).astype("float32")
    obj_t = paddle.to_tensor(tobj)
    # positives always count; negatives only where not ignored
    obj_w = paddle.to_tensor(
        tobj + (1.0 - tobj) * (1.0 - ignore))
    if gt_score is not None:
        # per-box confidence weights scale the positive cells
        gs = np.asarray(gt_score.numpy() if hasattr(gt_score, "numpy")
                        else gt_score, "float32")
        wpos = np.ones_like(tobj)
        for i in range(n):
            for bx, sc_, cl in zip(gb[i], gs[i], gl[i]):
                cx, cy, bw, bh = bx
                if bw <= 0 or bh <= 0:
                    continue
                gx, gy = int(cx * w), int(cy * h)
                if 0 <= gx < w and 0 <= gy < h:
                    wpos[i, :, gy, gx] = np.where(
                        tobj[i, :, gy, gx] > 0, sc_, 1.0)
        obj_w = obj_w * paddle.to_tensor(wpos)
    box_nchw = paddle.to_tensor(tbox).transpose([0, 1, 4, 2, 3])
    mask = obj_t.unsqueeze(2)
    axes = [1, 2, 3]
    loss_obj = (F.binary_cross_entropy_with_logits(
        pobj, obj_t, reduction="none") * obj_w).sum(axis=axes)
    loss_xy = (F.binary_cross_entropy_with_logits(
        pxy, box_nchw[:, :, 0:2], reduction="none") * mask
    ).sum(axis=[1, 2, 3, 4])
    loss_wh = (((pwh - box_nchw[:, :, 2:4]) ** 2) * mask
               ).sum(axis=[1, 2, 3, 4])
    smooth = 1.0 / class_num if use_label_smooth else 0.0
    cls_oh = F.one_hot(paddle.to_tensor(tcls), class_num
                       ).transpose([0, 1, 4, 2, 3])
    cls_t = cls_oh * (1.0 - smooth) + smooth * (1.0 / class_num)
    loss_cls = (F.binary_cross_entropy_with_logits(
        pcls, cls_t, reduction="none") * mask).sum(axis=[1, 2, 3, 4])
    return loss_obj + loss_xy + loss_wh + loss_cls
