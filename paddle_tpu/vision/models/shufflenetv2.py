"""ShuffleNetV2 — parity with python/paddle/vision/models/shufflenetv2.py."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, split


def channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = x.reshape([b, groups, c // groups, h, w])
    x = x.transpose([0, 2, 1, 3, 4])
    return x.reshape([b, c, h, w])


class InvertedResidual(nn.Layer):
    def __init__(self, in_channels, out_channels, stride, act_layer=nn.ReLU):
        super().__init__()
        self.stride = stride
        branch_features = out_channels // 2
        if self.stride == 1 and in_channels != branch_features * 2:
            raise ValueError("in_channels must equal out_channels when stride=1")

        if self.stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_channels, in_channels, 3, stride=stride,
                          padding=1, groups=in_channels, bias_attr=False),
                nn.BatchNorm2D(in_channels),
                nn.Conv2D(in_channels, branch_features, 1, bias_attr=False),
                nn.BatchNorm2D(branch_features), act_layer())
        branch2_in = in_channels if stride > 1 else branch_features
        self.branch2 = nn.Sequential(
            nn.Conv2D(branch2_in, branch_features, 1, bias_attr=False),
            nn.BatchNorm2D(branch_features), act_layer(),
            nn.Conv2D(branch_features, branch_features, 3, stride=stride,
                      padding=1, groups=branch_features, bias_attr=False),
            nn.BatchNorm2D(branch_features),
            nn.Conv2D(branch_features, branch_features, 1, bias_attr=False),
            nn.BatchNorm2D(branch_features), act_layer())

    def forward(self, x):
        if self.stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_repeats = [4, 8, 4]
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        stage_out = {0.25: [24, 24, 48, 96, 512],
                     0.33: [24, 32, 64, 128, 512],
                     0.5: [24, 48, 96, 192, 1024],
                     1.0: [24, 116, 232, 464, 1024],
                     1.5: [24, 176, 352, 704, 1024],
                     2.0: [24, 244, 488, 976, 2048]}[scale]

        self.conv1 = nn.Sequential(
            nn.Conv2D(3, stage_out[0], 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(stage_out[0]), act_layer())
        self.max_pool = nn.MaxPool2D(3, stride=2, padding=1)

        stages = []
        in_c = stage_out[0]
        for i, repeats in enumerate(stage_repeats):
            out_c = stage_out[i + 1]
            seq = [InvertedResidual(in_c, out_c, 2, act_layer)]
            for _ in range(repeats - 1):
                seq.append(InvertedResidual(out_c, out_c, 1, act_layer))
            stages.append(nn.Sequential(*seq))
            in_c = out_c
        self.stage2, self.stage3, self.stage4 = stages
        self.conv5 = nn.Sequential(
            nn.Conv2D(in_c, stage_out[-1], 1, bias_attr=False),
            nn.BatchNorm2D(stage_out[-1]), act_layer())
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(stage_out[-1], num_classes)

    def forward(self, x):
        x = self.conv1(x)
        x = self.max_pool(x)
        x = self.stage2(x)
        x = self.stage3(x)
        x = self.stage4(x)
        x = self.conv5(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def _shufflenet(scale, act="relu", pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load a local "
                         "state_dict instead")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, act="swish", pretrained=pretrained, **kwargs)
