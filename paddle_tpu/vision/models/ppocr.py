"""PP-OCRv3-class text recognizer (BASELINE.md row 6).

PP-OCRv3's recognition model is SVTR-LCNet (PaddleOCR
ppocr/modeling/{backbones/rec_svtrnet.py, heads/rec_ctc_head.py}): a conv
stem that patch-embeds the text line, mixing stages that alternate LOCAL
mixing (depthwise-conv over a neighborhood) with GLOBAL mixing (multi-head
self-attention over the width), then a CTC head.  The reference repo
in-tree only carries the kernel surface (warpctc / ctc_loss).

TPU-first notes: height is collapsed early so attention runs over the
width sequence only (short, ~40 tokens — dense attention, no flash
needed); all mixing is matmul/conv on MXU; CTC training reuses
vision.models.crnn.CTCHeadLoss (lax.scan forward algorithm)."""
from __future__ import annotations

import numpy as np

from ... import nn
from ...ops.manipulation import concat
from .crnn import CTCHeadLoss  # noqa: F401  (re-export for recipes)


class _ConvBNAct(nn.Layer):
    def __init__(self, cin, cout, k=3, stride=1, groups=1):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = nn.Swish()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class LocalMixBlock(nn.Layer):
    """SVTR local mixing: depthwise conv neighborhood mixing + pointwise
    channel MLP, both residual (rec_svtrnet.py ConvMixer shape)."""

    def __init__(self, dim, mlp_ratio=2.0):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim)
        self.dw = nn.Conv2D(dim, dim, 3, padding=1, groups=dim)
        self.norm2 = nn.LayerNorm(dim)
        hidden = int(dim * mlp_ratio)
        self.fc1 = nn.Linear(dim, hidden)
        self.fc2 = nn.Linear(hidden, dim)

    def forward(self, x):
        # x: [N, T, C] over a [H=1, W=T] lattice
        n, t, c = x.shape
        y = self.norm1(x).transpose([0, 2, 1]).reshape([n, c, 1, t])
        x = x + self.dw(y).reshape([n, c, t]).transpose([0, 2, 1])
        return x + self.fc2(nn.functional.gelu(self.fc1(self.norm2(x))))


class GlobalMixBlock(nn.Layer):
    """SVTR global mixing: MHSA over the width sequence + MLP."""

    def __init__(self, dim, num_heads=8, mlp_ratio=2.0):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim)
        self.attn = nn.MultiHeadAttention(dim, num_heads)
        self.norm2 = nn.LayerNorm(dim)
        hidden = int(dim * mlp_ratio)
        self.fc1 = nn.Linear(dim, hidden)
        self.fc2 = nn.Linear(hidden, dim)

    def forward(self, x):
        y = self.norm1(x)
        x = x + self.attn(y, y, y)
        return x + self.fc2(nn.functional.gelu(self.fc1(self.norm2(x))))


class SVTRRec(nn.Layer):
    """SVTR-tiny-class recognizer: [N, C, 32, W] text line -> CTC logits
    [N, W/4, num_classes] (class 0 = blank, reference convention)."""

    def __init__(self, num_classes, in_channels=3, dims=(64, 128, 256),
                 depths=(3, 6, 3), num_heads=8, max_width=320):
        super().__init__()
        # patch-embed stem: /4 in W, /8 in H (like PP-OCRv3's 32-high lines)
        self.stem = nn.Sequential(
            _ConvBNAct(in_channels, dims[0] // 2, 3, stride=2),
            _ConvBNAct(dims[0] // 2, dims[0], 3, stride=2))
        self.pool_h = nn.AdaptiveAvgPool2D((1, None))
        blocks = []
        dim = dims[0]
        for si, (d, depth) in enumerate(zip(dims, depths)):
            if d != dim:
                blocks.append(nn.Linear(dim, d))
                dim = d
            for bi in range(depth):
                # alternate local / global mixing (SVTR recipe: local early,
                # global late — here interleaved per stage parity)
                if si == 0 or bi % 2 == 0:
                    blocks.append(LocalMixBlock(d))
                else:
                    blocks.append(GlobalMixBlock(d, num_heads))
        self.blocks = nn.LayerList(blocks)
        self.norm = nn.LayerNorm(dims[-1])
        self.head = nn.Linear(dims[-1], num_classes)

    def forward(self, x):
        f = self.stem(x)                     # [N, C, H/4, W/4]
        f = self.pool_h(f)                   # [N, C, 1, W/4]
        n, c, _, w = f.shape
        seq = f.reshape([n, c, w]).transpose([0, 2, 1])   # [N, T, C]
        for blk in self.blocks:
            seq = blk(seq)
        return self.head(self.norm(seq))     # [N, T, num_classes]


def ppocrv3_rec(num_classes, **kw):
    """PP-OCRv3 recognition config (SVTR-LCNet class)."""
    return SVTRRec(num_classes, **kw)
