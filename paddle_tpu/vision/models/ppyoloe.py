"""PP-YOLOE-class anchor-free detector (BASELINE.md row 6).

The recipe lives in PaddleDetection (ppdet/modeling/{backbones/cspresnet.py,
necks/custom_pan.py, heads/ppyoloe_head.py}); the reference repo in-tree
only carries the kernel surface (yolo_box/nms).  This is a TPU-first
rebuild of the same architecture family:

* backbone `CSPRepResNet`: RepVGG-style blocks (3x3 + 1x1 train-time
  branches, `fuse()` collapses them into one deployable 3x3) in
  cross-stage-partial stages with effective-SE channel attention — all
  dense convs, MXU-friendly;
* neck `CSPPAN`: top-down + bottom-up path aggregation with CSP fusion;
* head `PPYOLOEHead`: decoupled cls/reg on anchor-free points with
  Distribution Focal Loss bins for box regression (reg_max discretized
  l/t/r/b), ESE attention per branch;
* loss: task-aligned assignment (top-k by cls^alpha * iou^beta among
  center-valid points — the TAL assigner), varifocal-style cls BCE
  weighted by the aligned metric, GIoU + DFL for boxes;
* inference decode -> vision.ops.nms (the reference kernel surface).

Static shapes throughout (padded gt boxes + masks) so the whole train step
jits; no dynamic control flow.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ... import nn
from ...core.op import apply_op
from ...core.tensor import Tensor
from ...ops.manipulation import concat
from .. import ops as vops


class ConvBN(nn.Layer):
    def __init__(self, cin, cout, k=3, stride=1, groups=1, act=True):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = nn.Swish() if act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act else x


class RepConvBlock(nn.Layer):
    """RepVGG block: parallel 3x3 + 1x1 (train); `fuse()` re-parameterizes
    into the single 3x3 the deploy graph uses (cspresnet.py RepVggBlock)."""

    def __init__(self, ch):
        super().__init__()
        self.conv3 = ConvBN(ch, ch, 3, act=False)
        self.conv1 = ConvBN(ch, ch, 1, act=False)
        self.act = nn.Swish()
        self._fused = None

    def forward(self, x):
        if self._fused is not None:
            return self.act(self._fused(x))
        return self.act(self.conv3(x) + self.conv1(x))

    def fuse(self):
        """Collapse both BN branches into one 3x3 conv (deploy mode)."""
        def bn_fold(conv, bn):
            w = conv.weight.numpy()
            gamma = bn.weight.numpy()
            beta = bn.bias.numpy()
            mean = bn._mean.numpy()
            var = bn._variance.numpy()
            std = np.sqrt(var + 1e-5)
            return w * (gamma / std)[:, None, None, None], \
                beta - mean * gamma / std
        w3, b3 = bn_fold(self.conv3.conv, self.conv3.bn)
        w1, b1 = bn_fold(self.conv1.conv, self.conv1.bn)
        w1_padded = np.pad(w1, ((0, 0), (0, 0), (1, 1), (1, 1)))
        fused = nn.Conv2D(w3.shape[1], w3.shape[0], 3, padding=1)
        import jax.numpy as jnp
        fused.weight._replace_(jnp.asarray(w3 + w1_padded), None)
        fused.bias._replace_(jnp.asarray(b3 + b1), None)
        self._fused = fused
        return self


class ESEAttn(nn.Layer):
    """Effective squeeze-excitation (one FC) — cspresnet.py EffectiveSELayer."""

    def __init__(self, ch):
        super().__init__()
        self.fc = nn.Conv2D(ch, ch, 1)

    def forward(self, x):
        s = x.mean(axis=[2, 3], keepdim=True)
        return x * nn.functional.sigmoid(self.fc(s))


class CSPRepStage(nn.Layer):
    def __init__(self, cin, cout, n_blocks, stride=2):
        super().__init__()
        self.down = ConvBN(cin, cout, 3, stride=stride)
        half = cout // 2
        self.a = ConvBN(cout, half, 1)
        self.b = ConvBN(cout, half, 1)
        self.blocks = nn.Sequential(*[RepConvBlock(half)
                                      for _ in range(n_blocks)])
        self.attn = ESEAttn(cout)
        self.fuse = ConvBN(cout, cout, 1)

    def forward(self, x):
        x = self.down(x)
        y = concat([self.a(x), self.blocks(self.b(x))], axis=1)
        return self.fuse(self.attn(y))


class CSPRepResNet(nn.Layer):
    """cspresnet.py CSPResNet shape: stem + 4 CSP-Rep stages; returns the
    last three scales (stride 8/16/32)."""

    def __init__(self, width=(32, 64, 128, 256, 512), depth=(1, 2, 2, 1),
                 in_channels=3):
        super().__init__()
        self.stem = nn.Sequential(ConvBN(in_channels, width[0], 3, stride=2),
                                  ConvBN(width[0], width[0], 3))
        self.stages = nn.LayerList([
            CSPRepStage(width[i], width[i + 1], depth[i])
            for i in range(4)])
        self.out_channels = width[2:]

    def forward(self, x):
        x = self.stem(x)
        feats = []
        for stage in self.stages:
            x = stage(x)
            feats.append(x)
        return feats[1:]  # strides 8, 16, 32


class CSPPAN(nn.Layer):
    """custom_pan.py CustomCSPPAN (compact): top-down fusion then
    bottom-up re-aggregation, CSP-Rep fusion at every junction."""

    def __init__(self, in_channels, out_ch=None):
        super().__init__()
        c3, c4, c5 = in_channels
        o3, o4, o5 = out_ch or in_channels
        self.reduce5 = ConvBN(c5, o5, 1)
        self.reduce4 = ConvBN(c4, o4, 1)
        self.reduce3 = ConvBN(c3, o3, 1)
        self.lat4 = ConvBN(o5, o4, 1)
        self.lat3 = ConvBN(o4, o3, 1)
        self.td4 = nn.Sequential(RepConvBlock(o4), ESEAttn(o4))
        self.td3 = nn.Sequential(RepConvBlock(o3), ESEAttn(o3))
        self.down3 = ConvBN(o3, o3, 3, stride=2)
        self.bu4 = ConvBN(o3 + o4, o4, 1)
        self.down4 = ConvBN(o4, o4, 3, stride=2)
        self.bu5 = ConvBN(o4 + o5, o5, 1)
        self.out_channels = (o3, o4, o5)

    def forward(self, feats):
        p3, p4, p5 = feats
        p5 = self.reduce5(p5)
        p4 = self.td4(self.reduce4(p4) +
                      nn.functional.interpolate(self.lat4(p5),
                                                scale_factor=2))
        p3 = self.td3(self.reduce3(p3) +
                      nn.functional.interpolate(self.lat3(p4),
                                                scale_factor=2))
        n4 = self.bu4(concat([self.down3(p3), p4], axis=1))
        n5 = self.bu5(concat([self.down4(n4), p5], axis=1))
        return [p3, n4, n5]


class PPYOLOEHead(nn.Layer):
    """ppyoloe_head.py ET-head: per-scale ESE-attended stem, decoupled
    cls logits [N, C, H, W] and DFL regression bins [N, 4*(reg_max+1), H, W]
    over anchor-free points."""

    def __init__(self, in_channels, num_classes=80, reg_max=16):
        super().__init__()
        self.num_classes = num_classes
        self.reg_max = reg_max
        self.stems_cls = nn.LayerList([ESEAttn(c) for c in in_channels])
        self.stems_reg = nn.LayerList([ESEAttn(c) for c in in_channels])
        self.cls_heads = nn.LayerList([
            nn.Conv2D(c, num_classes, 3, padding=1) for c in in_channels])
        self.reg_heads = nn.LayerList([
            nn.Conv2D(c, 4 * (reg_max + 1), 3, padding=1)
            for c in in_channels])
        # bias init: prior prob 0.01 (focal-style head init)
        prior = float(-math.log((1 - 0.01) / 0.01))
        import jax.numpy as jnp
        for h in self.cls_heads:
            h.bias._replace_(jnp.full(tuple(h.bias.shape), prior,
                                      jnp.float32), None)

    def forward(self, feats):
        cls_list, reg_list = [], []
        for i, f in enumerate(feats):
            cls_list.append(self.cls_heads[i](self.stems_cls[i](f) + f))
            reg_list.append(self.reg_heads[i](self.stems_reg[i](f) + f))
        return cls_list, reg_list


def _grid_points(shapes, strides):
    """Anchor-free point centers [(sum HW), 2] in image coords + stride
    per point."""
    pts, sts = [], []
    for (h, w), s in zip(shapes, strides):
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        ctr = (np.stack([xx, yy], -1).reshape(-1, 2) + 0.5) * s
        pts.append(ctr)
        sts.append(np.full((h * w,), s, np.float32))
    return (np.concatenate(pts).astype(np.float32),
            np.concatenate(sts))


class PPYOLOE(nn.Layer):
    """Full detector; `forward(images)` returns per-scale raw head outputs
    (training) — `decode()` turns them into boxes/scores, `predict()` adds
    NMS (vision.ops.nms, the reference kernel)."""

    STRIDES = (8, 16, 32)

    def __init__(self, num_classes=80, width=(32, 64, 128, 256, 512),
                 depth=(1, 2, 2, 1), reg_max=16, in_channels=3):
        super().__init__()
        self.backbone = CSPRepResNet(width, depth, in_channels)
        self.neck = CSPPAN(self.backbone.out_channels)
        self.head = PPYOLOEHead(self.neck.out_channels, num_classes,
                                reg_max)
        self.num_classes = num_classes
        self.reg_max = reg_max

    def forward(self, x):
        return self.head(self.neck(self.backbone(x)))

    def fuse(self):
        """Re-parameterize every RepConvBlock for deployment."""
        for layer in self.sublayers():
            if isinstance(layer, RepConvBlock):
                layer.fuse()
        return self

    def decode(self, outputs):
        """Head outputs -> (boxes [N, P, 4] xyxy, scores [N, P, C])."""
        cls_list, reg_list = outputs
        shapes = [tuple(c.shape[2:]) for c in cls_list]
        pts, sts = _grid_points(shapes, self.STRIDES)

        def raw(*flat):
            n = len(flat) // 2
            cls_l, reg_l = flat[:n], flat[n:]
            b = cls_l[0].shape[0]
            cls_cat = jnp.concatenate(
                [c.reshape(b, self.num_classes, -1) for c in cls_l], -1)
            reg_cat = jnp.concatenate(
                [r.reshape(b, 4 * (self.reg_max + 1), -1) for r in reg_l],
                -1)
            scores = jax.nn.sigmoid(jnp.transpose(cls_cat, (0, 2, 1)))
            dist = jnp.transpose(reg_cat, (0, 2, 1)).reshape(
                b, -1, 4, self.reg_max + 1)
            bins = jnp.arange(self.reg_max + 1, dtype=jnp.float32)
            ltrb = jnp.sum(jax.nn.softmax(dist, -1) * bins, -1)  # [B,P,4]
            p = jnp.asarray(pts)[None]
            s = jnp.asarray(sts)[None, :, None]
            x1y1 = p - ltrb[..., :2] * s
            x2y2 = p + ltrb[..., 2:] * s
            return jnp.concatenate([x1y1, x2y2], -1), scores

        flat = tuple(cls_list) + tuple(reg_list)
        return apply_op(raw, "ppyoloe_decode", flat, {})

    def predict(self, x, score_threshold=0.4, nms_threshold=0.5,
                max_dets=100):
        boxes, scores = self.decode(self(x))
        out = []
        for i in range(boxes.shape[0]):
            cls_best = scores[i].max(axis=-1)
            keep = vops.nms(boxes[i], iou_threshold=nms_threshold,
                            scores=cls_best,
                            score_threshold=score_threshold,
                            top_k=max_dets)
            out.append((boxes[i].numpy()[keep.numpy()],
                        scores[i].numpy()[keep.numpy()]))
        return out


class PPYOLOELoss(nn.Layer):
    """Task-aligned assignment + varifocal cls + GIoU + DFL (ppyoloe_head.py
    get_loss).  gt: boxes [N, M, 4] xyxy padded with zeros, labels
    [N, M] int (-1 = pad)."""

    def __init__(self, model: PPYOLOE, topk=9, alpha=1.0, beta=6.0,
                 cls_weight=1.0, iou_weight=2.5, dfl_weight=0.5):
        super().__init__()
        self.m = model
        self.topk = topk
        self.alpha, self.beta = alpha, beta
        self.w = (cls_weight, iou_weight, dfl_weight)

    def forward(self, outputs, gt_boxes, gt_labels):
        cls_list, reg_list = outputs
        m = self.m
        shapes = [tuple(c.shape[2:]) for c in cls_list]
        pts, sts = _grid_points(shapes, m.STRIDES)

        def raw(gtb, gtl, *flat):
            n = len(flat) // 2
            cls_l, reg_l = flat[:n], flat[n:]
            b = cls_l[0].shape[0]
            nc, rmax = m.num_classes, m.reg_max
            cls_cat = jnp.transpose(jnp.concatenate(
                [c.reshape(b, nc, -1) for c in cls_l], -1), (0, 2, 1))
            reg_cat = jnp.transpose(jnp.concatenate(
                [r.reshape(b, 4 * (rmax + 1), -1) for r in reg_l], -1),
                (0, 2, 1)).reshape(b, -1, 4, rmax + 1)
            p = jnp.asarray(pts)          # [P, 2]
            s = jnp.asarray(sts)          # [P]
            bins = jnp.arange(rmax + 1, dtype=jnp.float32)
            ltrb = jnp.sum(jax.nn.softmax(reg_cat, -1) * bins, -1)
            pred = jnp.concatenate([p[None] - ltrb[..., :2] * s[None, :, None],
                                    p[None] + ltrb[..., 2:] * s[None, :, None]],
                                   -1)   # [B, P, 4]

            def iou(a, g):
                # a [P,4], g [M,4] -> [P,M]
                lt = jnp.maximum(a[:, None, :2], g[None, :, :2])
                rb = jnp.minimum(a[:, None, 2:], g[None, :, 2:])
                wh = jnp.clip(rb - lt, 0)
                inter = wh[..., 0] * wh[..., 1]
                aa = jnp.prod(jnp.clip(a[:, 2:] - a[:, :2], 0), -1)
                ga = jnp.prod(jnp.clip(g[:, 2:] - g[:, :2], 0), -1)
                return inter / jnp.maximum(aa[:, None] + ga[None] - inter,
                                           1e-9)

            total_cls = total_iou = total_dfl = 0.0
            total_pos = 0.0
            for bi in range(b):
                g, gl = gtb[bi], gtl[bi]                 # [M,4], [M]
                valid_g = gl >= 0                        # [M]
                scores_d = jax.lax.stop_gradient(
                    jax.nn.sigmoid(cls_cat[bi]))         # [P,C]
                ious = iou(jax.lax.stop_gradient(pred[bi]), g)  # [P,M]
                safe_gl = jnp.clip(gl, 0, nc - 1)
                cls_g = scores_d[:, safe_gl]             # [P,M]
                metric = (cls_g ** self.alpha) * (ious ** self.beta)
                # center prior: point inside the gt box
                inside = ((p[:, None, 0] >= g[None, :, 0]) &
                          (p[:, None, 0] <= g[None, :, 2]) &
                          (p[:, None, 1] >= g[None, :, 1]) &
                          (p[:, None, 1] <= g[None, :, 3]))
                metric = jnp.where(inside & valid_g[None], metric, -1.0)
                # top-k per gt
                k = min(self.topk, metric.shape[0])
                thresh = jnp.sort(metric, axis=0)[-k][None]  # [1,M]
                cand = (metric >= jnp.maximum(thresh, 0)) & (metric > 0)
                # each point keeps its best gt only
                best_gt = jnp.argmax(jnp.where(cand, metric, -1), axis=1)
                is_pos = jnp.any(cand, axis=1)
                pos_iou = ious[jnp.arange(ious.shape[0]), best_gt]
                pos_metric = metric[jnp.arange(ious.shape[0]), best_gt]
                # normalized alignment target (TAL): metric scaled to iou
                norm = pos_metric * (pos_iou /
                                     jnp.maximum(pos_metric.max(), 1e-9))
                tgt_cls = jnp.zeros((p.shape[0], nc))
                tgt_score = jnp.where(is_pos, norm, 0.0)
                onehot = jax.nn.one_hot(safe_gl[best_gt], nc)
                tgt_cls = onehot * tgt_score[:, None]
                # varifocal-style BCE weight
                pr = jax.nn.sigmoid(cls_cat[bi])
                wgt = jnp.where(tgt_cls > 0, tgt_cls,
                                0.75 * (pr ** 2.0))
                bce = -(tgt_cls * jnp.log(jnp.clip(pr, 1e-9, 1.0)) +
                        (1 - tgt_cls) *
                        jnp.log(jnp.clip(1 - pr, 1e-9, 1.0)))
                total_cls = total_cls + jnp.sum(wgt * bce)

                gsel = g[best_gt]                        # [P,4]
                # GIoU on positives
                a = pred[bi]
                lt = jnp.maximum(a[:, :2], gsel[:, :2])
                rb = jnp.minimum(a[:, 2:], gsel[:, 2:])
                wh = jnp.clip(rb - lt, 0)
                inter = wh[:, 0] * wh[:, 1]
                area_a = jnp.prod(jnp.clip(a[:, 2:] - a[:, :2], 0), -1)
                area_g = jnp.prod(jnp.clip(gsel[:, 2:] - gsel[:, :2], 0), -1)
                union = jnp.maximum(area_a + area_g - inter, 1e-9)
                iou_pp = inter / union
                lt_c = jnp.minimum(a[:, :2], gsel[:, :2])
                rb_c = jnp.maximum(a[:, 2:], gsel[:, 2:])
                area_c = jnp.maximum(
                    jnp.prod(jnp.clip(rb_c - lt_c, 0), -1), 1e-9)
                giou = iou_pp - (area_c - union) / area_c
                total_iou = total_iou + jnp.sum(
                    jnp.where(is_pos, (1 - giou) * tgt_score, 0.0))

                # DFL: distance targets in stride units, two-bin soft CE
                d_tgt = jnp.concatenate(
                    [(p - gsel[:, :2]) / s[:, None],
                     (gsel[:, 2:] - p) / s[:, None]], -1)
                d_tgt = jnp.clip(d_tgt, 0, rmax - 0.01)
                dl = jnp.floor(d_tgt)
                wr = d_tgt - dl
                logp = jax.nn.log_softmax(reg_cat[bi], -1)
                li = dl.astype(jnp.int32)
                lp_l = jnp.take_along_axis(logp, li[..., None],
                                           -1)[..., 0]
                lp_r = jnp.take_along_axis(logp, (li + 1)[..., None],
                                           -1)[..., 0]
                dfl = -(lp_l * (1 - wr) + lp_r * wr).mean(-1)
                total_dfl = total_dfl + jnp.sum(
                    jnp.where(is_pos, dfl * tgt_score, 0.0))
                total_pos = total_pos + jnp.maximum(tgt_score.sum(), 1.0)

            wc, wi, wd = self.w
            return (wc * total_cls + wi * total_iou + wd * total_dfl) \
                / total_pos

        flat = tuple(cls_list) + tuple(reg_list)
        return apply_op(raw, "ppyoloe_loss",
                        (gt_boxes, gt_labels) + flat, {})


def ppyoloe_s(num_classes=80, **kw):
    """PP-YOLOE-s-class width/depth."""
    return PPYOLOE(num_classes, width=(32, 64, 128, 256, 512),
                   depth=(1, 2, 2, 1), **kw)


def ppyoloe_crn_s(num_classes=80, **kw):  # PaddleDetection naming alias
    return ppyoloe_s(num_classes, **kw)
