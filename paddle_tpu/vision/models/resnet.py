"""ResNet family — parity with python/paddle/vision/models/resnet.py
(ResNet:~class, BasicBlock, BottleneckBlock, resnet50 at resnet.py:396, plus
resnext/wide variants).

TPU notes: NCHW layout kept for API parity (XLA transposes to its preferred
layout during compilation); BatchNorm2D carries running stats; all convs are
bias-free + BN, so the whole stem fuses into MXU convolutions.
"""
from __future__ import annotations

from ... import nn


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        if groups != 1 or base_width != 64:
            raise ValueError("BasicBlock only supports groups=1, base_width=64")
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation,
                               stride=stride, groups=groups, dilation=dilation,
                               bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """resnet.py ResNet: depth selects the block layout; with_pool/num_classes
    control the head like the reference."""

    _spec = {18: (BasicBlock, [2, 2, 2, 2]),
             34: (BasicBlock, [3, 4, 6, 3]),
             50: (BottleneckBlock, [3, 4, 6, 3]),
             101: (BottleneckBlock, [3, 4, 23, 3]),
             152: (BottleneckBlock, [3, 8, 36, 3])}

    def __init__(self, block=None, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1, stem_s2d=False):
        super().__init__()
        # stem_s2d: run conv1 as a space-to-depth transform — input packed
        # 2x2 into channels ([B,3,H,W] -> [B,12,H/2,W/2]) and the 7x7/s2
        # kernel rearranged into an EXACTLY equivalent 4x4/s1 kernel over
        # 12 channels (MLPerf TPU ResNet trick: 4x the MXU lane occupancy
        # of the C=3 stem).  Same parameters, bitwise-same math modulo
        # reassociation; A/B'd on device in docs/PERF.md.
        self.stem_s2d = bool(stem_s2d)
        if block is None:
            block, layers = self._spec[depth]
        else:
            layers = self._spec[depth][1]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inplanes = 64
        self.dilation = 1

        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                nn.BatchNorm2D(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample, self.groups,
                        self.base_width)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width))
        return nn.Sequential(*layers)

    def _stem_s2d(self, x):
        """conv1 via space-to-depth: exact 7x7/s2 equivalence as a 4x4/s1
        conv on 2x2-packed input (kernel left-padded one row/col so the
        stride-2 taps align with the 2x2 packing)."""
        from ...core.op import apply_op

        w = self.conv1.weight      # [64, 3, 7, 7]

        def raw(xv, wv):
            import jax.numpy as jnp
            from jax import lax
            b, c, h, wd = xv.shape
            xp = xv.reshape(b, c, h // 2, 2, wd // 2, 2)
            xp = xp.transpose(0, 1, 3, 5, 2, 4).reshape(
                b, c * 4, h // 2, wd // 2)          # channel = (c, r, s)
            k8 = jnp.pad(wv, ((0, 0), (0, 0), (1, 0), (1, 0)))
            o, ci, _, _ = wv.shape
            # K'[o, (c,r,s), a, b] = K8[o, c, 2a+r, 2b+s]
            kp = k8.reshape(o, ci, 4, 2, 4, 2).transpose(0, 1, 3, 5, 2, 4) \
                .reshape(o, ci * 4, 4, 4)
            return lax.conv_general_dilated(
                xp, kp, window_strides=(1, 1),
                padding=((2, 1), (2, 1)),
                dimension_numbers=("NCHW", "OIHW", "NCHW"))

        return apply_op(raw, "resnet_stem_s2d", (x, w), {})

    def forward(self, x):
        if self.stem_s2d and x.shape[-1] % 2 == 0 and x.shape[-2] % 2 == 0:
            x = self.relu(self.bn1(self._stem_s2d(x)))
        else:
            # odd H/W can't 2x2-pack; the plain stem handles it (identical
            # function either way)
            x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def _resnet(arch, Block, depth, pretrained, **kwargs):
    if pretrained:
        raise ValueError(
            "pretrained weights are not bundled in this build; load a local "
            "state_dict with model.set_state_dict(paddle.load(path)) instead")
    return ResNet(Block, depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet("resnet18", BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet("resnet34", BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    """resnet.py:396 parity."""
    return _resnet("resnet50", BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet("resnet101", BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet("resnet152", BottleneckBlock, 152, pretrained, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    return _resnet("resnext50_32x4d", BottleneckBlock, 50, pretrained,
                   groups=32, width=4, **kwargs)


def resnext50_64x4d(pretrained=False, **kwargs):
    return _resnet("resnext50_64x4d", BottleneckBlock, 50, pretrained,
                   groups=64, width=4, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    return _resnet("resnext101_32x4d", BottleneckBlock, 101, pretrained,
                   groups=32, width=4, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    return _resnet("resnext101_64x4d", BottleneckBlock, 101, pretrained,
                   groups=64, width=4, **kwargs)


def resnext152_32x4d(pretrained=False, **kwargs):
    return _resnet("resnext152_32x4d", BottleneckBlock, 152, pretrained,
                   groups=32, width=4, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    return _resnet("resnext152_64x4d", BottleneckBlock, 152, pretrained,
                   groups=64, width=4, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    return _resnet("wide_resnet50_2", BottleneckBlock, 50, pretrained,
                   width=128, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    return _resnet("wide_resnet101_2", BottleneckBlock, 101, pretrained,
                   width=128, **kwargs)
