"""CRNN text recognizer — the PP-OCRv3-class recognition config from the
BASELINE matrix (conv feature extractor → BiLSTM sequence encoder → CTC
head; the reference recipe lives in PaddleOCR, built here from the in-repo
layer corpus + F.ctc_loss).
"""
from __future__ import annotations

from ... import nn


class CRNN(nn.Layer):
    """Input [N, C, H, W] (H fixed, e.g. 32) → logits [N, W/4, num_classes]
    for CTC (class 0 = blank, reference convention)."""

    def __init__(self, num_classes, in_channels=1, hidden_size=96,
                 channels=(32, 64, 128), img_h=32):
        super().__init__()
        if img_h % 8 != 0:
            raise ValueError("img_h must be divisible by 8")
        c1, c2, c3 = channels
        self.convs = nn.Sequential(
            nn.Conv2D(in_channels, c1, 3, padding=1), nn.BatchNorm2D(c1),
            nn.ReLU(), nn.MaxPool2D(2, 2),                  # H/2, W/2
            nn.Conv2D(c1, c2, 3, padding=1), nn.BatchNorm2D(c2),
            nn.ReLU(), nn.MaxPool2D(2, 2),                  # H/4, W/4
            nn.Conv2D(c2, c3, 3, padding=1), nn.BatchNorm2D(c3),
            nn.ReLU(), nn.MaxPool2D(kernel_size=(2, 1), stride=(2, 1)),
        )                                                   # H/8, W/4
        self.img_h = img_h
        feat_dim = c3 * (img_h // 8)
        self.lstm = nn.LSTM(feat_dim, hidden_size, direction="bidirect")
        self.fc = nn.Linear(2 * hidden_size, num_classes)

    def forward(self, x):
        if x.shape[2] != self.img_h:
            raise ValueError(
                f"CRNN built for input height {self.img_h}, got {x.shape[2]}")
        f = self.convs(x)                      # [N, C, H', W']
        n, c, h, w = f.shape
        f = f.transpose([0, 3, 1, 2]).reshape([n, w, c * h])  # [N, T, C*H']
        out, _ = self.lstm(f)                  # [N, T, 2*hidden]
        return self.fc(out)                    # [N, T, num_classes]


class CTCHeadLoss(nn.Layer):
    """CTC loss over CRNN logits (F.ctc_loss; blank=0)."""

    def __init__(self, blank=0):
        super().__init__()
        self.blank = blank

    def forward(self, logits, labels, input_lengths=None, label_lengths=None):
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F

        n, t, _ = logits.shape
        if input_lengths is None:
            input_lengths = paddle.to_tensor(np.full((n,), t, "int64"))
        if label_lengths is None:
            label_lengths = paddle.to_tensor(
                np.full((n,), labels.shape[1], "int64"))
        # pass batch-first [N,T,C]: F.ctc_loss's layout detection handles
        # the time-major swap itself (pre-transposing breaks when T == N)
        return F.ctc_loss(logits, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction="mean")


def crnn(num_classes, pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load a local "
                         "state_dict instead")
    return CRNN(num_classes, **kwargs)


def ctc_greedy_decode(logits, blank=0):
    """Collapse repeats then drop blanks (PP-OCR greedy decoder)."""
    import numpy as np

    # argmax on device first: the host transfer is the [N, T] int ids,
    # not the [N, T, C] float logits (a vocab-fold smaller download)
    pred = logits.argmax(-1)
    ids = pred.numpy()  # [N, T]
    results = []
    for row in ids:
        out = []
        prev = -1
        for tok in row:
            if tok != prev and tok != blank:
                out.append(int(tok))
            prev = tok
        results.append(out)
    return results
