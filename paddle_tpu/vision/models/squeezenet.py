"""SqueezeNet — parity with python/paddle/vision/models/squeezenet.py."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat


class MakeFire(nn.Layer):
    def __init__(self, in_channels, squeeze_channels, expand1x1_channels,
                 expand3x3_channels):
        super().__init__()
        self._conv = nn.Conv2D(in_channels, squeeze_channels, 1)
        self._conv_path1 = nn.Conv2D(squeeze_channels, expand1x1_channels, 1)
        self._conv_path2 = nn.Conv2D(squeeze_channels, expand3x3_channels, 3,
                                     padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self._conv(x))
        x1 = self.relu(self._conv_path1(x))
        x2 = self.relu(self._conv_path2(x))
        return concat([x1, x2], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool

        if version == "1.0":
            self._conv = nn.Conv2D(3, 96, 7, stride=2)
            self._fires = nn.Sequential(
                MakeFire(96, 16, 64, 64), MakeFire(128, 16, 64, 64),
                MakeFire(128, 32, 128, 128))
            self._fires2 = nn.Sequential(
                MakeFire(256, 32, 128, 128), MakeFire(256, 48, 192, 192),
                MakeFire(384, 48, 192, 192), MakeFire(384, 64, 256, 256))
            self._fires3 = MakeFire(512, 64, 256, 256)
        elif version == "1.1":
            self._conv = nn.Conv2D(3, 64, 3, stride=2, padding=1)
            self._fires = nn.Sequential(
                MakeFire(64, 16, 64, 64), MakeFire(128, 16, 64, 64))
            self._fires2 = nn.Sequential(
                MakeFire(128, 32, 128, 128), MakeFire(256, 32, 128, 128))
            self._fires3 = nn.Sequential(
                MakeFire(256, 48, 192, 192), MakeFire(384, 48, 192, 192),
                MakeFire(384, 64, 256, 256), MakeFire(512, 64, 256, 256))
        else:
            raise ValueError("version must be '1.0' or '1.1'")
        self.relu = nn.ReLU()
        self.pool = nn.MaxPool2D(3, 2)
        self.dropout = nn.Dropout(0.5)
        self.final_conv = nn.Conv2D(512, num_classes if num_classes > 0
                                    else 1000, 1)
        self.avgpool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.relu(self._conv(x))
        x = self.pool(x)
        x = self._fires(x)
        x = self.pool(x)
        x = self._fires2(x)
        x = self.pool(x)
        x = self._fires3(x)
        x = self.dropout(x)
        x = self.relu(self.final_conv(x))
        x = self.avgpool(x)
        return x.flatten(1)


def squeezenet1_0(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load a local "
                         "state_dict instead")
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load a local "
                         "state_dict instead")
    return SqueezeNet("1.1", **kwargs)
