"""paddle.vision.transforms.functional parity, numpy/PIL-backed (the data
pipeline runs on host CPU feeding the TPU; reference:
python/paddle/vision/transforms/functional.py + functional_cv2/pil.py)."""
from __future__ import annotations

import numbers

import numpy as np

from ...core.tensor import Tensor


def _to_numpy(img):
    if isinstance(img, Tensor):
        return img.numpy()
    if isinstance(img, np.ndarray):
        return img
    # PIL image
    return np.asarray(img)


def _is_pil(img):
    return not isinstance(img, (np.ndarray, Tensor))


def to_tensor(pic, data_format="CHW"):
    arr = _to_numpy(pic)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.dtype == np.uint8:
        arr = arr.astype("float32") / 255.0
    else:
        arr = arr.astype("float32")
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    from ...core.tensor import Tensor as T
    import jax.numpy as jnp
    return T(jnp.asarray(arr), _internal=True)


def resize(img, size, interpolation="bilinear"):
    arr = _to_numpy(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(size * w / h)
        else:
            nh, nw = int(size * h / w), size
    else:
        nh, nw = size
    ys = np.clip((np.arange(nh) + 0.5) * h / nh - 0.5, 0, h - 1)
    xs = np.clip((np.arange(nw) + 0.5) * w / nw - 0.5, 0, w - 1)
    if interpolation == "nearest":
        out = arr[np.round(ys).astype(int)][:, np.round(xs).astype(int)]
    else:  # bilinear
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        a = arr.astype("float64")
        out = (a[y0][:, x0] * (1 - wy) * (1 - wx) + a[y0][:, x1] * (1 - wy) * wx +
               a[y1][:, x0] * wy * (1 - wx) + a[y1][:, x1] * wy * wx)
        if arr.dtype == np.uint8:
            out = np.clip(np.round(out), 0, 255)
        out = out.astype(arr.dtype)
    return out[:, :, 0] if squeeze else out


def crop(img, top, left, height, width):
    arr = _to_numpy(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _to_numpy(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(arr, top, left, th, tw)


def hflip(img):
    return _to_numpy(img)[:, ::-1]


def vflip(img):
    return _to_numpy(img)[::-1]


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_numpy(img)
    if isinstance(padding, int):
        padding = (padding, padding, padding, padding)
    if len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    left, top, right, bottom = padding
    pads = [(top, bottom), (left, right)] + [(0, 0)] * (arr.ndim - 2)
    if padding_mode == "constant":
        return np.pad(arr, pads, mode="constant", constant_values=fill)
    return np.pad(arr, pads, mode={"edge": "edge", "reflect": "reflect",
                                   "symmetric": "symmetric"}[padding_mode])


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _to_numpy(img).astype("float32")
    mean = np.asarray(mean, "float32")
    std = np.asarray(std, "float32")
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    if isinstance(img, Tensor):  # keep the ToTensor → Normalize chain tensor
        import jax.numpy as jnp
        return Tensor(jnp.asarray(arr), _internal=True)
    return arr


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    arr = _to_numpy(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else \
        (center[1], center[0])
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    if expand:
        # canvas that contains the rotated corners (PIL expand semantics)
        oh = int(np.ceil(abs(h * cos) + abs(w * sin) - 1e-7))
        ow = int(np.ceil(abs(w * cos) + abs(h * sin) - 1e-7))
        ocy, ocx = (oh - 1) / 2.0, (ow - 1) / 2.0
    else:
        oh, ow, ocy, ocx = h, w, cy, cx
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    ys = cos * (yy - ocy) - sin * (xx - ocx) + cy
    xs = sin * (yy - ocy) + cos * (xx - ocx) + cx
    out = np.full((oh, ow, arr.shape[2]), fill, dtype=arr.dtype)
    if interpolation == "bilinear":
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        wy = (ys - y0)[..., None]
        wx = (xs - x0)[..., None]

        def at(yi, xi):
            inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
            v = arr[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)].astype(
                "float64")
            return np.where(inb[..., None], v, float(fill))

        res = (at(y0, x0) * (1 - wy) * (1 - wx) + at(y0, x0 + 1) * (1 - wy) * wx +
               at(y0 + 1, x0) * wy * (1 - wx) + at(y0 + 1, x0 + 1) * wy * wx)
        if arr.dtype == np.uint8:
            res = np.clip(np.round(res), 0, 255)
        out = res.astype(arr.dtype)
    else:
        yi = np.round(ys).astype(int)
        xi = np.round(xs).astype(int)
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        out[valid] = arr[yi[valid], xi[valid]]
    return out[:, :, 0] if squeeze else out


def to_grayscale(img, num_output_channels=1):
    arr = _to_numpy(img).astype("float32")
    gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    gray = gray.astype(_to_numpy(img).dtype)
    if num_output_channels == 3:
        return np.stack([gray] * 3, axis=-1)
    return gray[..., None]


def adjust_brightness(img, brightness_factor):
    arr = _to_numpy(img).astype("float32") * brightness_factor
    return np.clip(arr, 0, 255).astype(_to_numpy(img).dtype)


def adjust_contrast(img, contrast_factor):
    arr = _to_numpy(img).astype("float32")
    mean = to_grayscale(arr).mean()
    out = (arr - mean) * contrast_factor + mean
    return np.clip(out, 0, 255).astype(_to_numpy(img).dtype)


def adjust_hue(img, hue_factor):
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = _to_numpy(img).astype("float32") / 255.0
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    maxc = arr[..., :3].max(-1)
    minc = arr[..., :3].min(-1)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-9), 0)
    dn = np.maximum(d, 1e-9)
    h = np.where(maxc == r, (g - b) / dn % 6,
                 np.where(maxc == g, (b - r) / dn + 2, (r - g) / dn + 4)) / 6.0
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6)
    f = h * 6 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(int) % 6
    rgb = np.stack([
        np.choose(i, [v, q, p, p, t, v]),
        np.choose(i, [t, v, v, q, p, p]),
        np.choose(i, [p, p, t, v, v, q])], axis=-1)
    return np.clip(rgb * 255.0, 0, 255).astype(_to_numpy(img).dtype)


def _inverse_warp(arr, inv_fn, oh, ow, interpolation, fill):
    """Sample arr (HWC numpy) at source coords given by inv_fn(yy, xx) ->
    (ys, xs) — the shared inverse-mapping core of rotate/affine/
    perspective."""
    h, w = arr.shape[:2]
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    ys, xs = inv_fn(yy.astype("float64"), xx.astype("float64"))
    out = np.full((oh, ow, arr.shape[2]), fill, dtype=arr.dtype)
    if interpolation == "bilinear":
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        wy = (ys - y0)[..., None]
        wx = (xs - x0)[..., None]

        def at(yi, xi):
            inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
            v = arr[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)].astype(
                "float64")
            return np.where(inb[..., None], v, float(fill))

        res = (at(y0, x0) * (1 - wy) * (1 - wx)
               + at(y0, x0 + 1) * (1 - wy) * wx
               + at(y0 + 1, x0) * wy * (1 - wx)
               + at(y0 + 1, x0 + 1) * wy * wx)
        if arr.dtype == np.uint8:
            res = np.clip(np.round(res), 0, 255)
        out = res.astype(arr.dtype)
    else:
        yi = np.round(ys).astype(int)
        xi = np.round(xs).astype(int)
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        out[valid] = arr[yi[valid], xi[valid]]
    return out


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """transforms.functional.affine: rotation+translate+scale+shear about
    `center` (default image center), inverse-warp sampled."""
    arr = _to_numpy(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else \
        (center[1], center[0])
    rot = np.deg2rad(angle)
    sx, sy = [np.deg2rad(s) for s in
              (shear if isinstance(shear, (list, tuple)) else (shear, 0.0))]
    # forward matrix M = T(center+translate) R(rot) Shear S(scale) T(-center)
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    M = np.array([[d, -b], [-c, a]]) / (a * d - b * c) / scale  # inverse
    ty, tx = translate[1], translate[0]

    def inv(yy, xx):
        dy = yy - cy - ty
        dx = xx - cx - tx
        ys = M[0, 0] * dy + M[0, 1] * dx + cy
        xs = M[1, 0] * dy + M[1, 1] * dx + cx
        return ys, xs

    out = _inverse_warp(arr, inv, h, w, interpolation, fill)
    return out[:, :, 0] if squeeze else out


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """transforms.functional.perspective: maps the quad `startpoints` to
    `endpoints` (4 [x, y] pairs) and warps accordingly."""
    arr = _to_numpy(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    h, w = arr.shape[:2]
    # solve the 8-dof homography taking endpoints -> startpoints (inverse
    # map, so output pixels sample from the source quad)
    A, bvec = [], []
    for (dx, dy), (sx_, sy_) in zip(endpoints, startpoints):
        A.append([dx, dy, 1, 0, 0, 0, -sx_ * dx, -sx_ * dy])
        bvec.append(sx_)
        A.append([0, 0, 0, dx, dy, 1, -sy_ * dx, -sy_ * dy])
        bvec.append(sy_)
    coef = np.linalg.solve(np.asarray(A, "float64"),
                           np.asarray(bvec, "float64"))
    Hm = np.append(coef, 1.0).reshape(3, 3)

    def inv(yy, xx):
        den = Hm[2, 0] * xx + Hm[2, 1] * yy + Hm[2, 2]
        xs = (Hm[0, 0] * xx + Hm[0, 1] * yy + Hm[0, 2]) / den
        ys = (Hm[1, 0] * xx + Hm[1, 1] * yy + Hm[1, 2]) / den
        return ys, xs

    out = _inverse_warp(arr, inv, h, w, interpolation, fill)
    return out[:, :, 0] if squeeze else out


def erase(img, i, j, h, w, v, inplace=False):
    """transforms.functional.erase: overwrite the [i:i+h, j:j+w] patch
    with value(s) v.  Accepts HWC numpy/PIL or CHW Tensor like the
    reference."""
    from ...core.tensor import Tensor as _T
    if isinstance(img, _T):
        import jax.numpy as jnp
        val = img._value
        v_j = jnp.asarray(v, val.dtype)
        if v_j.ndim == 1:      # per-channel fill on the CHW layout
            v_j = v_j.reshape(-1, 1, 1)
        patch = jnp.broadcast_to(v_j, (val.shape[0], h, w))
        out = val.at[:, i:i + h, j:j + w].set(patch)
        if inplace:
            img._replace_(out, None)
            return img
        return _T(out, _internal=True)
    arr = _to_numpy(img).copy()
    v_arr = np.asarray(v, arr.dtype)
    if v_arr.ndim == 1:       # per-channel fill
        v_arr = v_arr.reshape(1, 1, -1)
    arr[i:i + h, j:j + w] = v_arr   # scalar / [C] / [h, w, C] all broadcast
    return arr
