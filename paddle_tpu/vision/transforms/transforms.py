"""paddle.vision.transforms parity (python/paddle/vision/transforms/
transforms.py): composable image transforms over numpy/PIL inputs."""
from __future__ import annotations

import numbers
import random

import numpy as np

from . import functional as F


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    """Reference BaseTransform: keys select which inputs are transformed; the
    simple single-image form is what the zoo uses."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = img if isinstance(img, np.ndarray) else np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            aspect = np.exp(random.uniform(np.log(self.ratio[0]),
                                           np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                patch = F.crop(arr, top, left, ch, cw)
                return F.resize(patch, self.size, self.interpolation)
        return F.resize(F.center_crop(arr, min(h, w)), self.size,
                        self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = img if isinstance(img, np.ndarray) else np.asarray(img)
        if self.padding is not None:
            arr = F.pad(arr, self.padding, self.fill, self.padding_mode)
        h, w = arr.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            arr = F.pad(arr, (max(tw - w, 0), max(th - h, 0)), self.fill,
                        self.padding_mode)
            h, w = arr.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(arr, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return F.hflip(img)
        return img if isinstance(img, np.ndarray) else np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return F.vflip(img)
        return img if isinstance(img, np.ndarray) else np.asarray(img)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format
        self.to_rgb = to_rgb

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format,
                           self.to_rgb)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = img if isinstance(img, np.ndarray) else np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img if isinstance(img, np.ndarray) else np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value should be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img if isinstance(img, np.ndarray) else np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img if isinstance(img, np.ndarray) else np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        arr = np.asarray(img).astype("float32")
        gray = F.to_grayscale(arr, 3).astype("float32")
        out = arr * factor + gray * (1 - factor)
        return np.clip(out, 0, 255).astype(np.asarray(img).dtype)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img if isinstance(img, np.ndarray) else np.asarray(img)
        factor = random.uniform(-self.value, self.value)
        return F.adjust_hue(img, factor)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        order = list(self.transforms)
        random.shuffle(order)
        for t in order:
            img = t(img)
        return img


class RandomAffine(BaseTransform):
    """transforms.RandomAffine: random rotation/translate/scale/shear
    drawn per call, applied via functional.affine."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        h, w = F._to_numpy(img).shape[:2]
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        else:
            tx = ty = 0.0
        scale = np.random.uniform(*self.scale) if self.scale else 1.0
        if self.shear is None:
            shear = (0.0, 0.0)
        elif np.isscalar(self.shear):
            shear = (np.random.uniform(-self.shear, self.shear), 0.0)
        else:
            sh = list(self.shear) + [0.0] * (4 - len(list(self.shear)))
            shear = (np.random.uniform(sh[0], sh[1]),
                     np.random.uniform(sh[2], sh[3]))
        return F.affine(img, angle, (tx, ty), scale, shear,
                         self.interpolation, self.fill, self.center)


class RandomPerspective(BaseTransform):
    """transforms.RandomPerspective: with probability `prob`, move each
    corner inward by up to distortion_scale of the half-extent."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        h, w = F._to_numpy(img).shape[:2]
        dx = int(self.distortion_scale * w / 2)
        dy = int(self.distortion_scale * h / 2)
        start = [[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]]
        rnd = lambda a: int(np.random.randint(0, a + 1)) if a > 0 else 0
        end = [[rnd(dx), rnd(dy)],
               [w - 1 - rnd(dx), rnd(dy)],
               [w - 1 - rnd(dx), h - 1 - rnd(dy)],
               [rnd(dx), h - 1 - rnd(dy)]]
        return F.perspective(img, start, end, self.interpolation,
                              self.fill)


class RandomErasing(BaseTransform):
    """transforms.RandomErasing: erase a random patch with `value` (or
    random noise when value == "random")."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        if not (0 <= prob <= 1):
            raise ValueError("prob should be in [0, 1]")
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr_like = F._to_numpy(img) if not hasattr(img, "_value") else None
        if arr_like is not None:
            h, w, c = arr_like.shape if arr_like.ndim == 3 else (
                *arr_like.shape, 1)
        else:
            c, h, w = img.shape
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            aspect = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                              np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * aspect)))
            ew = int(round(np.sqrt(target / aspect)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                if self.value == "random":
                    v = np.random.standard_normal((eh, ew, c) if
                                                  arr_like is not None
                                                  else (c, eh, ew))
                    if arr_like is not None and arr_like.dtype == np.uint8:
                        v = np.clip(v * 64 + 128, 0, 255)
                else:
                    v = self.value
                return F.erase(img, i, j, eh, ew, v, self.inplace)
        return img
