from .functional import (  # noqa: F401
    adjust_brightness, adjust_contrast, adjust_hue, affine, center_crop,
    crop, erase, hflip, normalize, pad, perspective, resize, rotate,
    to_grayscale, to_tensor, vflip,
)
from .transforms import (  # noqa: F401
    BaseTransform, BrightnessTransform, CenterCrop, ColorJitter, Compose,
    ContrastTransform, Grayscale, HueTransform, Normalize, Pad, RandomCrop,
    RandomHorizontalFlip, RandomResizedCrop, RandomRotation, RandomVerticalFlip,
    RandomAffine, RandomErasing, RandomPerspective, Resize,
    SaturationTransform, ToTensor, Transpose,
)
