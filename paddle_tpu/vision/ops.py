"""paddle.vision.ops parity — detection ops (reference: vision/ops.py
yolo_box:250, deform_conv2d:427, psroi_pool:1057, roi_align:1302, nms:1517,
backed there by CUDA kernels).

TPU-native formulations: everything is expressed as dense gathers / one-hot
matmuls with static shapes so XLA can compile it; nms uses an O(N^2) IoU
matrix + lax.fori_loop greedy sweep (the data-dependent early-exit loop the
CUDA kernel uses has no XLA analog).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.op import apply_op
from ..core.tensor import Tensor
from ..nn.layer_base import Layer


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# -- yolo_box ----------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """vision/ops.py:250 parity: decode a YOLOv3 head [N, A*(5+C), H, W] into
    boxes [N, A*H*W, 4] and scores [N, A*H*W, C]."""
    anchors_np = np.asarray(anchors, np.float32).reshape(-1, 2)
    na = anchors_np.shape[0]

    def decode(xv, img):
        n, _, h, w = xv.shape
        if iou_aware:
            # iou-aware head (PP-YOLO): x = [N, na + na*(5+C), H, W], the
            # leading na channels are predicted IoU; objectness becomes
            # conf^(1-f) * iou^f (yolo_box kernel iou_aware branch)
            iou_pred = jax.nn.sigmoid(xv[:, :na].reshape(n, na, h, w))
            xv = xv[:, na:]
        pred = xv.reshape(n, na, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=xv.dtype)
        gy = jnp.arange(h, dtype=xv.dtype)
        bx = (jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y -
              (scale_x_y - 1) / 2 + gx[None, None, None, :]) / w
        by = (jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y -
              (scale_x_y - 1) / 2 + gy[None, None, :, None]) / h
        anc = jnp.asarray(anchors_np, xv.dtype)
        bw = jnp.exp(pred[:, :, 2]) * anc[None, :, 0, None, None] / \
            (w * downsample_ratio)
        bh = jnp.exp(pred[:, :, 3]) * anc[None, :, 1, None, None] / \
            (h * downsample_ratio)
        conf = jax.nn.sigmoid(pred[:, :, 4])
        if iou_aware:
            conf = conf ** (1.0 - iou_aware_factor) * \
                iou_pred ** iou_aware_factor
        probs = jax.nn.sigmoid(pred[:, :, 5:])
        scores = conf[:, :, None] * probs
        # below-threshold boxes are zeroed like the reference
        keep = (conf >= conf_thresh)[:, :, None]
        img_h = img[:, 0].reshape(n, 1, 1, 1)
        img_w = img[:, 1].reshape(n, 1, 1, 1)
        x0 = (bx - bw / 2) * img_w
        y0 = (by - bh / 2) * img_h
        x1 = (bx + bw / 2) * img_w
        y1 = (by + bh / 2) * img_h
        if clip_bbox:
            x0 = jnp.clip(x0, 0, img_w - 1)
            y0 = jnp.clip(y0, 0, img_h - 1)
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
        boxes = jnp.stack([x0, y0, x1, y1], axis=2)
        boxes = boxes * (conf >= conf_thresh)[:, :, None]
        boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(n, na * h * w, 4)
        scores = (scores * keep).transpose(0, 1, 3, 4, 2).reshape(
            n, na * h * w, class_num)
        return boxes, scores

    b, s = decode(_unwrap(x), _unwrap(img_size).astype(jnp.float32))
    return Tensor(b, _internal=True), Tensor(s, _internal=True)


# -- roi_align ---------------------------------------------------------------

def _bilinear_gather(feat, ys, xs):
    """feat [C,H,W]; ys/xs arbitrary shape -> [C, *shape] bilinear samples."""
    h, w = feat.shape[-2], feat.shape[-1]
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    y1, x1 = y0 + 1, x0 + 1
    wy1 = ys - y0
    wx1 = xs - x0
    wy0, wx0 = 1 - wy1, 1 - wx1

    def at(yi, xi):
        yc = jnp.clip(yi.astype(jnp.int32), 0, h - 1)
        xc = jnp.clip(xi.astype(jnp.int32), 0, w - 1)
        return feat[:, yc, xc]

    valid = ((ys >= -1) & (ys <= h) & (xs >= -1) & (xs <= w))
    out = (at(y0, x0) * (wy0 * wx0) + at(y0, x1) * (wy0 * wx1) +
           at(y1, x0) * (wy1 * wx0) + at(y1, x1) * (wy1 * wx1))
    return out * valid


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """vision/ops.py:1302 parity.  boxes: [R, 4] (x0,y0,x1,y1) in image
    coords; boxes_num: rois per batch image.

    sampling_ratio<=0 means adaptive ceil(bin_size) samples per bin like the
    reference kernel; per-roi counts need concrete box values, so under a jit
    trace the adaptive path falls back to the reference's common effective
    ratio of 2 (static shapes are an XLA requirement).
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    bv_probe = _unwrap(boxes)
    if sampling_ratio > 0:
        sr_list = None
        sr = sampling_ratio
    elif isinstance(bv_probe, jax.core.Tracer):
        sr_list = None
        sr = 2
    else:
        # per-roi adaptive ratios from concrete boxes (reference semantics)
        b_np = np.asarray(bv_probe)
        rh_np = (b_np[:, 3] - b_np[:, 1]) * spatial_scale
        rw_np = (b_np[:, 2] - b_np[:, 0]) * spatial_scale
        if not aligned:
            rh_np = np.maximum(rh_np, 1.0)
            rw_np = np.maximum(rw_np, 1.0)
        sr_list = [(max(1, int(np.ceil(rh_np[i] / ph))),
                    max(1, int(np.ceil(rw_np[i] / pw))))
                   for i in range(b_np.shape[0])]
        sr = None

    def impl(xv, bv, bn):
        # map each roi to its image via boxes_num prefix sums
        r = bv.shape[0]
        starts = jnp.cumsum(bn) - bn
        roi_img = jnp.sum(jnp.arange(r)[:, None] >=
                          starts[None, :], axis=1) - 1

        off = 0.5 if aligned else 0.0
        x0 = bv[:, 0] * spatial_scale - off
        y0 = bv[:, 1] * spatial_scale - off
        x1 = bv[:, 2] * spatial_scale - off
        y1 = bv[:, 3] * spatial_scale - off
        rw = x1 - x0
        rh = y1 - y0
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw

        def roi_pool(ri, sr_h, sr_w):
            feat = xv[roi_img[ri]]
            iy = (jnp.arange(ph)[:, None] +
                  (jnp.arange(sr_h)[None, :] + 0.5) / sr_h)
            ix = (jnp.arange(pw)[:, None] +
                  (jnp.arange(sr_w)[None, :] + 0.5) / sr_w)
            yy = (y0[ri] + iy * bin_h[ri]).reshape(-1)  # ph*sr_h
            xx = (x0[ri] + ix * bin_w[ri]).reshape(-1)  # pw*sr_w
            grid_y = jnp.repeat(yy, xx.shape[0])
            grid_x = jnp.tile(xx, yy.shape[0])
            vals = _bilinear_gather(feat, grid_y, grid_x)
            vals = vals.reshape(feat.shape[0], ph, sr_h, pw, sr_w)
            return vals.mean(axis=(2, 4))

        if sr_list is not None:
            return jnp.stack([roi_pool(i, *sr_list[i]) for i in range(r)])
        return jax.vmap(lambda ri: roi_pool(ri, sr, sr))(jnp.arange(r))

    return apply_op(impl, "roi_align", (x, boxes, boxes_num), {})


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """vision/ops.py:1057 parity: position-sensitive RoI average pooling.
    Input channels C = output_channels * ph * pw; bin (i,j) pools its own
    channel slice."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def impl(xv, bv, bn):
        c = xv.shape[1]
        out_c = c // (ph * pw)
        r = bv.shape[0]
        starts = jnp.cumsum(bn) - bn
        roi_img = jnp.sum(jnp.arange(r)[:, None] >= starts[None, :], axis=1) - 1
        h, w = xv.shape[2], xv.shape[3]

        x0 = bv[:, 0] * spatial_scale
        y0 = bv[:, 1] * spatial_scale
        x1 = bv[:, 2] * spatial_scale
        y1 = bv[:, 3] * spatial_scale
        rw = jnp.maximum(x1 - x0, 0.1)
        rh = jnp.maximum(y1 - y0, 0.1)
        bin_h = rh / ph
        bin_w = rw / pw

        yy = jnp.arange(h, dtype=xv.dtype)
        xx = jnp.arange(w, dtype=xv.dtype)

        def per_roi(ri):
            feat = xv[roi_img[ri]].reshape(out_c, ph, pw, h, w)
            ys = y0[ri] + jnp.arange(ph, dtype=xv.dtype) * bin_h[ri]
            ye = ys + bin_h[ri]
            xs = x0[ri] + jnp.arange(pw, dtype=xv.dtype) * bin_w[ri]
            xe = xs + bin_w[ri]
            my = ((yy[None, :] >= jnp.floor(ys)[:, None]) &
                  (yy[None, :] < jnp.ceil(ye)[:, None])).astype(xv.dtype)
            mx = ((xx[None, :] >= jnp.floor(xs)[:, None]) &
                  (xx[None, :] < jnp.ceil(xe)[:, None])).astype(xv.dtype)
            # bin (i,j) mean over its mask, from its own channel group
            area = jnp.maximum(my.sum(1)[:, None] * mx.sum(1)[None, :], 1.0)
            pooled = jnp.einsum("opqhw,ph,qw->opq", feat, my, mx) / area
            return pooled

        return jax.vmap(per_roi)(jnp.arange(r))

    return apply_op(impl, "psroi_pool", (x, boxes, boxes_num), {})


# -- box_coder ---------------------------------------------------------------

def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """vision/ops box_coder parity (encode/decode_center_size; the R-CNN
    bbox-delta transform).  For decode, `axis` selects which dim of the
    [row, col, 4] target the prior boxes broadcast over: axis=0 -> prior
    per COLUMN (cpu/box_coder.cc:122 `j * len`), axis=1 -> prior per ROW
    (`i * len`).  Encode ignores axis like the reference."""
    if axis not in (0, 1):
        raise ValueError(f"box_coder axis must be 0 or 1, got {axis}")
    if isinstance(prior_box_var, (list, tuple)):
        prior_box_var = Tensor(jnp.asarray(prior_box_var, jnp.float32),
                               _internal=True)

    def impl(pb, pbv, tb):
        px0, py0, px1, py1 = pb[:, 0], pb[:, 1], pb[:, 2], pb[:, 3]
        norm = 0.0 if box_normalized else 1.0
        pw = px1 - px0 + norm
        ph = py1 - py0 + norm
        pcx = px0 + pw * 0.5
        pcy = py0 + ph * 0.5
        if pbv is None:
            var = jnp.ones((4,), tb.dtype)
        else:
            var = pbv
        if code_type == "encode_center_size":
            tx0, ty0, tx1, ty1 = tb[:, 0], tb[:, 1], tb[:, 2], tb[:, 3]
            tw = tx1 - tx0 + norm
            th = ty1 - ty0 + norm
            tcx = tx0 + tw * 0.5
            tcy = ty0 + th * 0.5
            if pbv is not None and pbv.ndim == 2:
                vx, vy, vw, vh = var[:, 0], var[:, 1], var[:, 2], var[:, 3]
            else:
                vx, vy, vw, vh = var[0], var[1], var[2], var[3]
            out = jnp.stack([
                (tcx[:, None] - pcx[None, :]) / pw[None, :] / vx,
                (tcy[:, None] - pcy[None, :]) / ph[None, :] / vy,
                jnp.log(tw[:, None] / pw[None, :]) / vw,
                jnp.log(th[:, None] / ph[None, :]) / vh,
            ], axis=-1)  # [T, P, 4]
            return out
        if code_type == "decode_center_size":
            # tb: [row, col, 4] deltas (or [N, 4] broadcast on prior axis);
            # the prior stats broadcast over dim (1-axis)
            d = tb if tb.ndim == 3 else (tb[:, None, :] if axis == 0
                                         else tb[None, :, :])

            def bc(t):
                return t[None, :] if axis == 0 else t[:, None]

            if pbv is not None and pbv.ndim == 2:
                v = pbv[None, :, :] if axis == 0 else pbv[:, None, :]
            else:
                v = var.reshape(1, 1, 4)
            cx = d[..., 0] * v[..., 0] * bc(pw) + bc(pcx)
            cy = d[..., 1] * v[..., 1] * bc(ph) + bc(pcy)
            w = jnp.exp(d[..., 2] * v[..., 2]) * bc(pw)
            h = jnp.exp(d[..., 3] * v[..., 3]) * bc(ph)
            return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                              cx + w * 0.5 - norm, cy + h * 0.5 - norm],
                             axis=-1)
        raise ValueError(f"unknown code_type {code_type!r}")

    return apply_op(impl, "box_coder",
                    (prior_box, prior_box_var, target_box), {})


# -- nms ---------------------------------------------------------------------

def _iou_matrix(boxes):
    areas = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = areas[:, None] + areas[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """vision/ops.py:1517 parity: greedy hard-NMS; returns kept indices
    sorted by descending score.  Category-aware when category_idxs given
    (boxes of different categories never suppress each other)."""
    bv = _unwrap(boxes)
    n = bv.shape[0]
    sv = _unwrap(scores) if scores is not None else jnp.ones((n,), bv.dtype)

    iou = _iou_matrix(bv)
    if category_idxs is not None:
        cv = _unwrap(category_idxs)
        same = cv[:, None] == cv[None, :]
        iou = jnp.where(same, iou, 0.0)

    order = jnp.argsort(-sv)

    def body(i, keep):
        idx = order[i]
        # suppressed if any higher-scoring KEPT box overlaps > threshold
        sup = jnp.any((iou[idx, order[:n]] > iou_threshold) &
                      keep[order[:n]] & (jnp.arange(n) < i))
        return keep.at[idx].set(~sup)

    keep = jax.lax.fori_loop(0, n, body, jnp.zeros((n,), bool))
    kept_sorted = order[keep[order]]
    if top_k is not None:
        kept_sorted = kept_sorted[:top_k]
    return Tensor(kept_sorted, _internal=True)


# -- deform_conv2d -----------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """vision/ops.py:427 parity (DCNv1 when mask is None, DCNv2 with mask):
    bilinear-sample input at offset positions, then a dense matmul — the
    gather+GEMM decomposition of the CUDA kernel, which is also the
    MXU-friendly layout."""
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)

    def impl(xv, ov, wv, bv2, mv):
        n, c, h, w = xv.shape
        oc, cpg, kh, kw = wv.shape
        sh, sw = stride
        ph_, pw_ = padding
        dh, dw = dilation
        out_h = (h + 2 * ph_ - (dh * (kh - 1) + 1)) // sh + 1
        out_w = (w + 2 * pw_ - (dw * (kw - 1) + 1)) // sw + 1
        xp = jnp.pad(xv, ((0, 0), (0, 0), (ph_, ph_), (pw_, pw_)))

        base_y = (jnp.arange(out_h) * sh)[:, None, None] + \
            (jnp.arange(kh) * dh)[None, :, None]  # [oh,kh,1]
        base_x = (jnp.arange(out_w) * sw)[:, None, None] + \
            (jnp.arange(kw) * dw)[None, :, None]  # [ow,kw,1]
        # offsets: [N, dg*2*kh*kw, oh, ow] (y then x per kernel point)
        ov_r = ov.reshape(n, deformable_groups, 2, kh * kw, out_h, out_w)

        def per_image(xi, oi, mi):
            def per_dg(g):
                oy = oi[g, 0].reshape(kh, kw, out_h, out_w)
                ox = oi[g, 1].reshape(kh, kw, out_h, out_w)
                gy = (jnp.arange(out_h)[None, None, :, None] * sh +
                      jnp.arange(kh)[:, None, None, None] * dh + oy)
                gx = (jnp.arange(out_w)[None, None, None, :] * sw +
                      jnp.arange(kw)[None, :, None, None] * dw + ox)
                cg = c // deformable_groups
                feat = xi[g * cg:(g + 1) * cg]
                samp = _bilinear_gather(feat, gy.reshape(-1), gx.reshape(-1))
                samp = samp.reshape(cg, kh, kw, out_h, out_w)
                if mi is not None:
                    mg = mi[g].reshape(kh, kw, out_h, out_w)
                    samp = samp * mg[None]
                return samp

            cols = jnp.concatenate([per_dg(g)
                                    for g in range(deformable_groups)], axis=0)
            # cols: [C,kh,kw,oh,ow]; grouped conv = one einsum per the
            # gather+GEMM decomposition
            cpg_in = c // groups
            opg = oc // groups
            cols_g = cols.reshape(groups, cpg_in, kh, kw, out_h, out_w)
            w_g = wv.reshape(groups, opg, cpg, kh, kw)
            out = jnp.einsum("gcpqij,gocpq->goij", cols_g, w_g)
            return out.reshape(oc, out_h, out_w)

        mvv = [None] * n if mv is None else \
            mv.reshape(n, deformable_groups, kh * kw, out_h, out_w)
        outs = jnp.stack([
            per_image(xp[i], ov_r[i],
                      None if mv is None else mvv[i]) for i in range(n)])
        if bv2 is not None:
            outs = outs + bv2[None, :, None, None]
        return outs

    return apply_op(impl, "deform_conv2d", (x, offset, weight, bias, mask), {})


class DeformConv2D(Layer):
    """vision/ops.py DeformConv2D layer parity."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..nn import initializer as _I  # noqa: F401
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        fan_in = in_channels // groups * kernel_size[0] * kernel_size[1]
        bound = 1.0 / np.sqrt(fan_in)
        from ..nn.initializer import Uniform
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + tuple(kernel_size),
            attr=weight_attr, default_initializer=Uniform(-bound, bound))
        self.bias = self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-bound, bound))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self._stride,
                             self._padding, self._dilation,
                             self._deformable_groups, self._groups, mask)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),  # noqa: A002
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD anchor generator (phi prior_box_kernel.cc): returns
    (boxes [H, W, P, 4] normalized xyxy, variances [H, W, P, 4]).
    `min_max_aspect_ratios_order=False` (the reference default) emits
    [min, ar..., max] per min-size; True emits [min, max, ar...]."""
    feat = _unwrap(input)
    img = _unwrap(image)
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    ar_tail = [a for a in ars if abs(a - 1.0) >= 1e-6]

    whs = []
    for i, ms in enumerate(min_sizes):
        ar_boxes = [(ms * np.sqrt(a), ms / np.sqrt(a)) for a in ar_tail]
        max_box = []
        if max_sizes:
            sq = np.sqrt(ms * max_sizes[i])
            max_box = [(sq, sq)]
        if min_max_aspect_ratios_order:
            whs += [(ms, ms)] + max_box + ar_boxes
        else:
            whs += [(ms, ms)] + ar_boxes + max_box

    p = len(whs)
    cx = (np.arange(fw) + offset) * step_w
    cy = (np.arange(fh) + offset) * step_h
    gx, gy = np.meshgrid(cx, cy)               # [H, W]
    w = np.asarray([wh[0] for wh in whs])      # [P]
    h = np.asarray([wh[1] for wh in whs])
    boxes = np.stack([
        (gx[..., None] - w * 0.5) / iw,
        (gy[..., None] - h * 0.5) / ih,
        (gx[..., None] + w * 0.5) / iw,
        (gy[..., None] + h * 0.5) / ih,
    ], axis=-1).astype(np.float32)             # [H, W, P, 4]
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    vars_ = np.broadcast_to(np.asarray(variance, np.float32),
                            (fh, fw, p, 4)).copy()
    return (Tensor(jnp.asarray(boxes), _internal=True),
            Tensor(jnp.asarray(vars_), _internal=True))


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, rois_num=None,
                   name=None):
    """Per-class NMS (phi multiclass_nms3 CPU kernel — host-side
    POST-PROCESSING in the reference too, not a traced op).

    bboxes [M, 4] or batched [N, M, 4]; scores [C, M] or [N, C, M].
    Returns (dets [K, 6] rows [label, score, x1, y1, x2, y2], index [K],
    nms_rois_num [N]).  keep_top_k/nms_top_k of -1 mean unlimited;
    `normalized=False` uses the pixel (+1 extent) IoU convention;
    `nms_eta` < 1 adaptively decays the threshold like the reference.
    """
    b = np.asarray(_unwrap(bboxes))
    s = np.asarray(_unwrap(scores))
    batched = b.ndim == 3
    if not batched:
        b = b[None]
        s = s[None]
    norm = 0.0 if normalized else 1.0

    def _np_nms(boxes, cscores):
        order = np.argsort(-cscores)
        if nms_top_k > -1:
            order = order[:nms_top_k]
        keep = []
        thresh = nms_threshold
        areas = (boxes[:, 2] - boxes[:, 0] + norm) * \
            (boxes[:, 3] - boxes[:, 1] + norm)
        while order.size:
            i = order[0]
            keep.append(int(i))
            if order.size == 1:
                break
            rest = order[1:]
            lt = np.maximum(boxes[i, :2], boxes[rest, :2])
            rb = np.minimum(boxes[i, 2:], boxes[rest, 2:])
            wh = np.clip(rb - lt + norm, 0, None)
            inter = wh[:, 0] * wh[:, 1]
            iou = inter / np.maximum(areas[i] + areas[rest] - inter, 1e-10)
            order = rest[iou <= thresh]
            if nms_eta < 1.0 and thresh > 0.5:
                thresh *= nms_eta
        return keep

    all_dets, all_picks, per_img = [], [], []
    base = 0
    for n in range(b.shape[0]):
        dets, picks = [], []
        for c in range(s.shape[1]):
            if c == background_label:
                continue
            cs = s[n, c]
            cand = np.where(cs > score_threshold)[0]
            if cand.size == 0:
                continue
            for k in _np_nms(b[n][cand], cs[cand]):
                gi = int(cand[k])
                dets.append([float(c), float(cs[gi])] + b[n, gi].tolist())
                picks.append(base + gi)
        if dets:
            order = np.argsort(-np.asarray([d[1] for d in dets]))
            if keep_top_k > -1:
                order = order[:keep_top_k]
            dets = np.asarray(dets, np.float32)[order]
            picks = np.asarray(picks, np.int64)[order]
        else:
            dets = np.zeros((0, 6), np.float32)
            picks = np.zeros((0,), np.int64)
        all_dets.append(dets)
        all_picks.append(picks)
        per_img.append(len(dets))
        base += b.shape[1]
    dets = np.concatenate(all_dets) if all_dets else \
        np.zeros((0, 6), np.float32)
    picks = np.concatenate(all_picks) if all_picks else \
        np.zeros((0,), np.int64)
    return (Tensor(jnp.asarray(dets), _internal=True),
            Tensor(jnp.asarray(picks), _internal=True),
            Tensor(jnp.asarray(per_img, jnp.int32), _internal=True))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """vision/ops.py:1175 RoIPool (max pooling over quantized RoI bins —
    the pre-RoIAlign detector head)."""
    xv = _unwrap(x)
    bx = _unwrap(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    n, c, h, w = xv.shape
    bn = np.asarray(_unwrap(boxes_num))
    img_of_box = np.repeat(np.arange(len(bn)), bn)
    outs = []
    bx_np = np.asarray(bx)
    for bi in range(bx_np.shape[0]):
        img = int(img_of_box[bi]) if len(img_of_box) else 0
        x1, y1, x2, y2 = [v * spatial_scale for v in bx_np[bi]]
        x1, y1 = int(np.round(x1)), int(np.round(y1))
        x2, y2 = int(np.round(x2)), int(np.round(y2))
        rh = max(y2 - y1 + 1, 1)
        rw = max(x2 - x1 + 1, 1)
        bins = []
        for i in range(ph):
            for j in range(pw):
                ys = y1 + int(np.floor(i * rh / ph))
                ye = y1 + int(np.ceil((i + 1) * rh / ph))
                xs = x1 + int(np.floor(j * rw / pw))
                xe = x1 + int(np.ceil((j + 1) * rw / pw))
                ys, ye = np.clip([ys, ye], 0, h)
                xs, xe = np.clip([xs, xe], 0, w)
                if ye <= ys or xe <= xs:
                    bins.append(jnp.zeros((c,), xv.dtype))
                else:
                    bins.append(jnp.max(xv[img, :, ys:ye, xs:xe],
                                        axis=(1, 2)))
        outs.append(jnp.stack(bins, axis=1).reshape(c, ph, pw))
    out = jnp.stack(outs) if outs else jnp.zeros((0, c, ph, pw), xv.dtype)
    return Tensor(out, _internal=True)


class RoIPool(Layer):
    """vision/ops.py RoIPool layer form."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """vision/ops.py:1819 Matrix NMS (SOLOv2): soft suppression via the
    decay matrix min-IoU formulation — parallel, no sequential greedy
    loop, so it maps to dense TPU math directly."""
    bx = np.asarray(_unwrap(bboxes))   # [N, M, 4]
    sc = np.asarray(_unwrap(scores))   # [N, C, M]
    all_out, all_idx, rois_num = [], [], []
    n, cnum, m = sc.shape
    for b in range(n):
        dets, idxs = [], []
        for c in range(cnum):
            if c == background_label:
                continue
            s = sc[b, c]
            keep = np.where(s > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-s[keep])][:nms_top_k]
            boxes_c = bx[b, order]
            scores_c = s[order]
            # pairwise IoU of the kept, score-sorted boxes
            x1 = np.maximum(boxes_c[:, None, 0], boxes_c[None, :, 0])
            y1 = np.maximum(boxes_c[:, None, 1], boxes_c[None, :, 1])
            x2 = np.minimum(boxes_c[:, None, 2], boxes_c[None, :, 2])
            y2 = np.minimum(boxes_c[:, None, 3], boxes_c[None, :, 3])
            ext = 0.0 if normalized else 1.0
            iw = np.clip(x2 - x1 + ext, 0, None)
            ih = np.clip(y2 - y1 + ext, 0, None)
            inter = iw * ih
            area = ((boxes_c[:, 2] - boxes_c[:, 0] + ext)
                    * (boxes_c[:, 3] - boxes_c[:, 1] + ext))
            iou = inter / np.maximum(area[:, None] + area[None, :] - inter,
                                     1e-10)
            iou = np.triu(iou, k=1)
            # decay: for each box j, over higher-scored i
            comp = iou.max(axis=0)      # max IoU of each box vs any higher
            # decay_ij = f(iou_ij) / f(comp_i): the suppressor row i is
            # itself discounted by ITS best suppressor (comp along i)
            if use_gaussian:
                decay = np.exp(-(iou ** 2 - comp[:, None] ** 2)
                               / gaussian_sigma)
            else:
                decay = (1 - iou) / np.maximum(1 - comp[:, None], 1e-10)
            decay = np.where(np.triu(np.ones_like(iou), k=1) > 0, decay,
                             np.inf).min(axis=0)
            decay[0] = 1.0
            new_scores = scores_c * decay
            ok = new_scores > post_threshold
            for t in np.where(ok)[0]:
                dets.append([c, new_scores[t], *boxes_c[t]])
                idxs.append(b * m + order[t])
        dets = np.asarray(dets, np.float32).reshape(-1, 6)
        idxs = np.asarray(idxs, np.int64)
        if dets.shape[0] > keep_top_k:
            top = np.argsort(-dets[:, 1])[:keep_top_k]
            dets, idxs = dets[top], idxs[top]
        all_out.append(dets)
        all_idx.append(idxs)
        rois_num.append(dets.shape[0])
    out = Tensor(np.concatenate(all_out) if all_out else
                 np.zeros((0, 6), np.float32))
    ret = [out]
    if return_rois_num:
        ret.append(Tensor(np.asarray(rois_num, np.int32)))
    if return_index:
        ret.append(Tensor(np.concatenate(all_idx) if all_idx else
                          np.zeros((0,), np.int64)))
    return tuple(ret) if len(ret) > 1 else out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """vision/ops.py:836: route each RoI to its FPN level by
    sqrt(area)/refer_scale (the FPN paper's assignment)."""
    rois = np.asarray(_unwrap(fpn_rois))
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.clip(ws * hs, 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    n_levels = max_level - min_level + 1
    outs, out_nums, order = [], [], []
    for L in range(min_level, min_level + n_levels):
        idx = np.where(lvl == L)[0]
        outs.append(Tensor(rois[idx].astype(rois.dtype)))
        order.append(idx)
        if rois_num is not None:
            rn = np.asarray(_unwrap(rois_num))
            img_of = np.repeat(np.arange(len(rn)), rn)
            out_nums.append(Tensor(np.bincount(
                img_of[idx], minlength=len(rn)).astype(np.int32)))
    restore = np.argsort(np.concatenate(order)) if order else \
        np.zeros((0,), np.int64)
    if rois_num is not None:
        return outs, Tensor(restore.astype(np.int32)), out_nums
    return outs, Tensor(restore.astype(np.int32))


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """vision/ops.py:1668 RPN proposal generation: decode anchors with
    deltas, clip, filter small, NMS per image."""
    sc = np.asarray(_unwrap(scores))          # [N, A, H, W]
    bd = np.asarray(_unwrap(bbox_deltas))     # [N, 4A, H, W]
    im = np.asarray(_unwrap(img_size))        # [N, 2]
    an = np.asarray(_unwrap(anchors)).reshape(-1, 4)   # [H*W*A, 4]
    va = np.asarray(_unwrap(variances)).reshape(-1, 4)
    n, a, h, w = sc.shape
    off = 1.0 if pixel_offset else 0.0
    rois_out, num_out, scores_out = [], [], []
    for i in range(n):
        s = sc[i].transpose(1, 2, 0).reshape(-1)
        d = bd[i].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, anc, var = s[order], d[order], an[order], va[order]
        aw = anc[:, 2] - anc[:, 0] + off
        ah = anc[:, 3] - anc[:, 1] + off
        acx = anc[:, 0] + aw * 0.5
        acy = anc[:, 1] + ah * 0.5
        cx = var[:, 0] * d[:, 0] * aw + acx
        cy = var[:, 1] * d[:, 1] * ah + acy
        bw = aw * np.exp(np.minimum(var[:, 2] * d[:, 2], 10.0))
        bh = ah * np.exp(np.minimum(var[:, 3] * d[:, 3], 10.0))
        props = np.stack([cx - bw * 0.5, cy - bh * 0.5,
                          cx + bw * 0.5 - off, cy + bh * 0.5 - off], 1)
        H, W = im[i]
        props[:, 0::2] = np.clip(props[:, 0::2], 0, W - off)
        props[:, 1::2] = np.clip(props[:, 1::2], 0, H - off)
        keep = np.where((props[:, 2] - props[:, 0] + off >= min_size)
                        & (props[:, 3] - props[:, 1] + off >= min_size))[0]
        props, s = props[keep], s[keep]
        sel = np.asarray(nms(Tensor(props.astype(np.float32)),
                             iou_threshold=nms_thresh,
                             scores=Tensor(s.astype(np.float32)),
                             top_k=post_nms_top_n).numpy())
        rois_out.append(props[sel])
        scores_out.append(s[sel].reshape(-1, 1))
        num_out.append(len(sel))
    rois = Tensor(np.concatenate(rois_out).astype(np.float32))
    rscores = Tensor(np.concatenate(scores_out).astype(np.float32))
    if return_rois_num:
        return rois, rscores, Tensor(np.asarray(num_out, np.int32))
    return rois, rscores


def read_file(filename, name=None):
    """vision/ops.py:960: file bytes as a uint8 tensor."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(np.frombuffer(data, np.uint8).copy())


def decode_jpeg(x, mode="unchanged", name=None):
    """vision/ops.py:1006: decode a JPEG byte tensor to CHW uint8.  The
    reference uses nvjpeg; here PIL/cv2 decode (loud error when neither
    is installed — no silent wrong pixels)."""
    data = bytes(np.asarray(_unwrap(x)).astype(np.uint8).tobytes())
    import io as _io
    try:
        from PIL import Image
        img = Image.open(_io.BytesIO(data))
        if mode == "gray":
            img = img.convert("L")
        elif mode in ("rgb", "unchanged"):
            img = img.convert("RGB") if mode == "rgb" else img
        arr = np.asarray(img)
    except ImportError:
        try:
            import cv2
            flag = {"gray": cv2.IMREAD_GRAYSCALE,
                    "rgb": cv2.IMREAD_COLOR}.get(mode, cv2.IMREAD_UNCHANGED)
            arr = cv2.imdecode(np.frombuffer(data, np.uint8), flag)
            if arr.ndim == 3:
                arr = arr[..., ::-1]   # cv2 decodes BGR; match PIL's RGB
        except ImportError as e:
            raise ImportError(
                "decode_jpeg needs PIL or cv2 installed") from e
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(np.ascontiguousarray(arr))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """vision/ops.py yolo_loss — delegates to the YOLOv3Loss layer math
    (vision/models/yolo.py), which implements the yolov3_loss_op
    assignment + BCE/L1 terms for ONE detection head."""
    from .models.yolo import yolo_head_loss
    return yolo_head_loss(x, gt_box, gt_label, anchors, anchor_mask,
                          class_num, ignore_thresh, downsample_ratio,
                          gt_score, use_label_smooth, scale_x_y)
