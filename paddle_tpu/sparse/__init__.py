"""paddle.sparse parity (python/paddle/incubate/sparse → paddle.sparse):
SparseCooTensor/SparseCsrTensor (phi/core sparse_coo_tensor.h /
sparse_csr_tensor.h analogs) over jax.experimental.sparse BCOO.

The reference keeps a dedicated sparse kernel tree (phi/kernels/sparse/, 29
files); XLA's sparse support is BCOO-based, so COO is the native layout here
and CSR is a view-style wrapper that converts through COO.

Autograd design: sparse VALUES ride the eager tape.  Every op's value
compute runs through ``apply_op`` with the (concrete, host-side) index
structure closed over as static data, and each sparse tensor keeps a taped
``Tensor`` view of its values — so dense↔sparse compositions
(Conv3D → relu → pooling → dense head) backprop to weights and inputs just
like the reference's sparse grad kernels.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "add", "multiply", "matmul", "masked_matmul",
           "relu", "transpose", "is_same_shape",
           "conv3d", "subm_conv3d", "max_pool3d", "fused_attention",
           "to_dense", "to_sparse_coo", "to_sparse_csr", "values",
           "coalesce", "full_like", "acos", "acosh"]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _apply(fn, name, args):
    from ..core.op import apply_op
    return apply_op(fn, name, args, {})


class SparseCooTensor:
    """COO sparse tensor (dense_tensor.h's SparseCooTensor analog).

    ``_vt`` is the taped Tensor view of the stored values; ``_bcoo`` mirrors
    it for jsparse interop (same underlying buffer).
    """

    def __init__(self, bcoo: jsparse.BCOO, values_t: Tensor | None = None):
        self._bcoo = bcoo
        self._vt = values_t

    @classmethod
    def _make(cls, values_t: Tensor, indices, shape):
        bcoo = jsparse.BCOO((values_t._value, jnp.asarray(indices)),
                            shape=tuple(shape))
        return cls(bcoo, values_t)

    # -- paddle surface ------------------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T, _internal=True)  # [ndim, nnz]

    def values(self) -> Tensor:
        if self._vt is None:
            self._vt = Tensor(self._bcoo.data, _internal=True)
        return self._vt

    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def to_dense(self) -> Tensor:
        idx = self._bcoo.indices
        shape = self._bcoo.shape
        nsp = idx.shape[1]

        def scatter(v):
            dense = jnp.zeros(shape, v.dtype)
            return dense.at[tuple(idx[:, d] for d in range(nsp))].add(v)

        return _apply(scatter, "sparse_to_dense", (self.values(),))

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor.from_coo(self)

    def coalesce(self) -> "SparseCooTensor":
        idx = np.asarray(self._bcoo.indices)
        uniq, inv = np.unique(idx, axis=0, return_inverse=True)
        inv_j, n = jnp.asarray(inv), len(uniq)
        out_t = _apply(
            lambda v: jax.ops.segment_sum(v, inv_j, num_segments=n),
            "sparse_coalesce", (self.values(),))
        return SparseCooTensor._make(out_t, uniq, self._bcoo.shape)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR view (crows/cols/values surface); stored as COO underneath."""

    def __init__(self, crows, cols, values, shape):
        self._crows = jnp.asarray(_val(crows), jnp.int64)
        self._cols = jnp.asarray(_val(cols), jnp.int64)
        self._vt = values if isinstance(values, Tensor) else \
            Tensor(jnp.asarray(_val(values)), _internal=True)
        self._shape = tuple(int(s) for s in shape)

    @classmethod
    def from_coo(cls, coo: SparseCooTensor):
        if len(coo.shape) != 2:
            raise ValueError(
                f"CSR conversion supports 2-D tensors, got shape "
                f"{coo.shape}; keep batched sparse data in COO")
        coo = coo.coalesce()
        # coalesce's np.unique(axis=0) already lexsorts indices in
        # (row, col) order — no reorder gather needed
        idx = np.asarray(coo._bcoo.indices)
        rows, cols = idx[:, 0], idx[:, 1]
        vals_t = coo.values()
        n_rows = coo.shape[0]
        crows = np.zeros(n_rows + 1, np.int64)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows)
        return cls(crows, cols, vals_t, coo.shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._vt.dtype

    def crows(self) -> Tensor:
        return Tensor(self._crows, _internal=True)

    def cols(self) -> Tensor:
        return Tensor(self._cols, _internal=True)

    def values(self) -> Tensor:
        return self._vt

    def nnz(self) -> int:
        return int(self._cols.shape[0])

    def to_sparse_coo(self, sparse_dim=2) -> SparseCooTensor:
        crows = np.asarray(self._crows)
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        idx = np.stack([rows, np.asarray(self._cols)], axis=1)
        return SparseCooTensor._make(self._vt, idx, self._shape)

    def to_dense(self) -> Tensor:
        return self.to_sparse_coo().to_dense()

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


# -- constructors ------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    # shape inference runs on the HOST copy BEFORE the device transfer:
    # construction-time indices are host data (lists / numpy) in the
    # common path, so the np reduction costs nothing — the previous
    # device-side max forced a transfer + reduce + sync round trip per
    # construction (and synced even when `shape` was provided)
    raw = indices._value if isinstance(indices, Tensor) else indices
    host_idx = np.asarray(raw, dtype=np.int64)
    if host_idx.ndim != 2:
        raise ValueError("indices must be [sparse_dim, nnz]")
    idx = jnp.asarray(host_idx)
    vals = values if isinstance(values, Tensor) else \
        Tensor(jnp.asarray(_val(values)), _internal=True)
    if dtype is not None:
        vals = vals.astype(dtype)
    if shape is None:
        shape = tuple(int(i) for i in host_idx.max(axis=1) + 1)
    return SparseCooTensor._make(vals, idx.T, tuple(shape))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    vals = values if isinstance(values, Tensor) else \
        Tensor(jnp.asarray(_val(values)), _internal=True)
    if dtype is not None:
        vals = vals.astype(dtype)
    return SparseCsrTensor(crows, cols, vals, shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


# -- ops (phi/kernels/sparse parity) -----------------------------------------

def _coerce_coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def add(x, y, name=None):
    x, y = _coerce_coo(x), _coerce_coo(y)
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        idx = np.concatenate([np.asarray(x._bcoo.indices),
                              np.asarray(y._bcoo.indices)], axis=0)
        uniq, inv = np.unique(idx, axis=0, return_inverse=True)
        inv_j, n = jnp.asarray(inv), len(uniq)
        out_t = _apply(
            lambda a, b: jax.ops.segment_sum(
                jnp.concatenate([a, b], axis=0), inv_j, num_segments=n),
            "sparse_add", (x.values(), y.values()))
        return SparseCooTensor._make(out_t, uniq, x._bcoo.shape)
    dense = y if isinstance(x, SparseCooTensor) else x
    sp = x if isinstance(x, SparseCooTensor) else y
    dense = dense if isinstance(dense, Tensor) else \
        Tensor(jnp.asarray(_val(dense)), _internal=True)
    return sp.to_dense() + dense


def multiply(x, y, name=None):
    x = _coerce_coo(x)
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        y = _coerce_coo(y).to_dense()
    y = y if isinstance(y, Tensor) else \
        Tensor(jnp.asarray(_val(y)), _internal=True)
    idx = x._bcoo.indices
    nsp = idx.shape[1]

    def mul(v, d):
        gathered = d[tuple(idx[:, k] for k in range(nsp))] if d.ndim else d
        return v * gathered

    out_t = _apply(mul, "sparse_multiply", (x.values(), y))
    return SparseCooTensor._make(out_t, idx, x._bcoo.shape)


def matmul(x, y, name=None):
    """sparse @ dense → dense (phi sparse matmul kernels)."""
    x = _coerce_coo(x)
    idx, shape = x._bcoo.indices, x._bcoo.shape
    y = y if isinstance(y, Tensor) else \
        Tensor(jnp.asarray(_val(y)), _internal=True)
    return _apply(
        lambda v, d: jsparse.BCOO((v, idx), shape=shape) @ d,
        "sparse_matmul", (x.values(), y))


def masked_matmul(x, y, mask, name=None):
    """dense @ dense sampled at mask's sparsity (SDDMM)."""
    mask = _coerce_coo(mask)
    idx = mask._bcoo.indices
    rows, cols = idx[:, 0], idx[:, 1]
    x = x if isinstance(x, Tensor) else \
        Tensor(jnp.asarray(_val(x)), _internal=True)
    y = y if isinstance(y, Tensor) else \
        Tensor(jnp.asarray(_val(y)), _internal=True)
    out_t = _apply(
        lambda a, b: jnp.einsum("nk,nk->n", a[rows, :], b[:, cols].T),
        "sparse_masked_matmul", (x, y))
    return SparseCooTensor._make(out_t, idx, mask._bcoo.shape)


def transpose(x, perm, name=None):
    x = _coerce_coo(x)
    nsp = x._bcoo.indices.shape[1]
    nd = len(x._bcoo.shape)
    perm = list(perm)
    sp_perm, dense_perm = perm[:nsp], perm[nsp:]
    if sorted(sp_perm) != list(range(nsp)) or \
            sorted(dense_perm) != list(range(nsp, nd)):
        raise NotImplementedError(
            f"hybrid COO transpose must permute sparse dims (first {nsp}) "
            f"and dense dims separately; got perm={perm}")
    idx = np.asarray(x._bcoo.indices)[:, sp_perm]
    shape = tuple(x._bcoo.shape[p] for p in perm)
    if dense_perm == list(range(nsp, nd)):
        vals = x.values()
    else:
        # permute the dense block axes of the values [nnz, *dense]
        vperm = [0] + [p - nsp + 1 for p in dense_perm]
        from ..core.op import apply_op
        vals = apply_op(lambda v: jnp.transpose(v, vperm),
                        "sparse_transpose_dense", (x.values(),), {})
    return SparseCooTensor._make(vals, idx, shape)


# -- value-wise unary family (sparse_ops.yaml: abs/sin/.../sqrt applied to
# stored values only, zero-preserving by construction) ------------------------

def _valuewise(fn, opname=None):
    op_label = opname or f"sparse_{getattr(fn, '__name__', 'valuewise')}"

    def op(x, name=None):
        x = _coerce_coo(x)
        out_t = _apply(fn, op_label, (x.values(),))
        return SparseCooTensor._make(out_t, x._bcoo.indices, x._bcoo.shape)
    return op


abs = _valuewise(jnp.abs)          # noqa: A001
sin = _valuewise(jnp.sin)
sinh = _valuewise(jnp.sinh)
asin = _valuewise(jnp.arcsin)
asinh = _valuewise(jnp.arcsinh)
tan = _valuewise(jnp.tan)
tanh = _valuewise(jnp.tanh)
atan = _valuewise(jnp.arctan)
atanh = _valuewise(jnp.arctanh)
acos = _valuewise(jnp.arccos)
acosh = _valuewise(jnp.arccosh)
sqrt = _valuewise(jnp.sqrt)
square = _valuewise(jnp.square)
log1p = _valuewise(jnp.log1p)
expm1 = _valuewise(jnp.expm1)
relu = _valuewise(lambda v: jnp.maximum(v, 0), "sparse_relu")
relu6 = _valuewise(lambda v: jnp.clip(v, 0, 6), "sparse_relu6")


def leaky_relu(x, negative_slope=0.01, name=None):
    return _valuewise(lambda v: jnp.where(v > 0, v, negative_slope * v),
                      "sparse_leaky_relu")(x)


def pow(x, factor, name=None):  # noqa: A001
    return _valuewise(lambda v: v ** factor, "sparse_pow")(x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, name=None):
    # bias on a sparse tensor only touches stored values (yaml scale op)
    return _valuewise(lambda v: v * scale + bias if bias_after_scale
                      else (v + bias) * scale, "sparse_scale")(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    x = _coerce_coo(x)
    idx = x._bcoo.indices.astype(index_dtype) if index_dtype else \
        x._bcoo.indices
    vals = x.values()
    if value_dtype:
        vals = vals.astype(value_dtype)
    return SparseCooTensor._make(vals, idx, x._bcoo.shape)


def subtract(x, y, name=None):
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        return add(x, scale(_coerce_coo(y), -1.0))
    y = y if isinstance(y, Tensor) else \
        Tensor(jnp.asarray(_val(y)), _internal=True)
    return add(x, -y)


def divide(x, y, name=None):
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        raise ValueError("sparse/sparse divide is undefined off the "
                         "intersection; densify first")
    y = y if isinstance(y, Tensor) else \
        Tensor(jnp.asarray(_val(y)), _internal=True)
    return multiply(x, 1.0 / y)


def divide_scalar(x, scalar, name=None):
    return _valuewise(lambda v: v / scalar, "sparse_divide_scalar")(x)


def mv(x, vec, name=None):
    """sparse matrix @ dense vector (sparse_ops.yaml mv)."""
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    """beta*input + alpha*(sparse x @ dense y)."""
    input = input if isinstance(input, Tensor) else \
        Tensor(jnp.asarray(_val(input)), _internal=True)
    return beta * input + alpha * matmul(x, y)


def softmax(x, axis=-1, name=None):
    """Row-wise softmax over stored values only (phi sparse softmax:
    implicit zeros do NOT participate) — one segment_max/segment_sum pass
    over the CSR values, O(1) device dispatches regardless of row count."""
    if axis not in (-1, 1):
        raise ValueError("sparse softmax supports the last axis only")
    csr = SparseCsrTensor.from_coo(_coerce_coo(x)) \
        if isinstance(x, SparseCooTensor) else x
    crows = np.asarray(csr._crows)
    counts = np.diff(crows)
    row_ids = jnp.asarray(np.repeat(np.arange(len(counts)), counts))
    nrows = len(counts)

    def smax(v):
        row_max = jax.ops.segment_max(v, row_ids, num_segments=nrows)
        e = jnp.exp(v - row_max[row_ids])
        row_sum = jax.ops.segment_sum(e, row_ids, num_segments=nrows)
        return e / row_sum[row_ids]

    out_t = _apply(smax, "sparse_softmax", (csr.values(),))
    return SparseCsrTensor(csr._crows, csr._cols, out_t, csr.shape)


def to_dense(x, name=None):
    return x.to_dense()


def to_sparse_coo(x, sparse_dim=None, name=None):
    """Dense → COO.  `sparse_dim` keeps only the leading sparse_dim axes
    sparse; trailing axes stay dense blocks (the reference's hybrid COO,
    e.g. [nnz, C] values for a [N, D, H, W, C] voxel grid)."""
    if isinstance(x, SparseCsrTensor):
        if sparse_dim not in (None, 2):
            raise NotImplementedError(
                f"CSR -> COO is 2-sparse-dim by construction; got "
                f"sparse_dim={sparse_dim}")
        return x.to_sparse_coo()
    if isinstance(x, SparseCooTensor):
        return x
    xv = _val(x)
    nd = xv.ndim
    sd = nd if sparse_dim is None else int(sparse_dim)
    if not 1 <= sd <= nd:
        raise ValueError(f"sparse_dim must be in [1, {nd}], got {sd}")
    arr = np.asarray(xv)
    nonzero = arr != 0
    if sd < nd:       # a site is stored if ANY of its dense block is nonzero
        nonzero = nonzero.any(axis=tuple(range(sd, nd)))
    idx = np.argwhere(nonzero)
    x_t = x if isinstance(x, Tensor) else Tensor(xv, _internal=True)
    vals_t = _apply(
        lambda d: d[tuple(jnp.asarray(idx[:, k]) for k in range(sd))],
        "sparse_from_dense", (x_t,))
    return SparseCooTensor._make(vals_t, idx, xv.shape)


def to_sparse_csr(x, name=None):
    if isinstance(x, SparseCooTensor):
        return x.to_sparse_csr()
    if isinstance(x, SparseCsrTensor):
        return x
    return to_sparse_coo(x).to_sparse_csr()


def values(x, name=None):
    return x.values()


def coalesce(x, name=None):
    return _coerce_coo(x).coalesce()


def full_like(x, value, dtype=None, name=None):
    """coo_full_like/csr_full_like: same sparsity, constant stored values."""
    if isinstance(x, SparseCsrTensor):
        vals = jnp.full((x.nnz(),), value, dtype or x._vt._value.dtype)
        return SparseCsrTensor(x._crows, x._cols, vals, x.shape)
    x = _coerce_coo(x)
    vals = Tensor(jnp.full(x._bcoo.data.shape, value,
                           dtype or x._bcoo.data.dtype), _internal=True)
    return SparseCooTensor._make(vals, x._bcoo.indices, x._bcoo.shape)


# -- sparse 3-D conv / pooling (sparse_ops.yaml conv3d:83, maxpool:349) ------
#
# The reference builds a gather-scatter "rulebook" on device
# (phi/kernels/sparse/gpu/conv.cu).  Eager sparse indices here are concrete
# host data, so the rulebook is built VECTORIZED on host (per-offset numpy
# candidate generation + one np.unique / sorted-match), memoized per
# (sparsity pattern, geometry), and the VALUE compute — the FLOPs — runs
# as one gather+einsum+segment_sum per call through apply_op, which keeps
# dense `kernel` (and the sparse input values) on the autograd tape.

def _to3(v):
    return (v, v, v) if isinstance(v, (int, np.integer)) else tuple(v)


_RULEBOOK_CACHE: dict = {}


def _match_rows(table, queries):
    """For each query row, index into `table` (or -1).  Both [n, k] int."""
    if len(table) == 0 or len(queries) == 0:
        return np.full(len(queries), -1, np.int64)
    dt = np.dtype((np.void, table.dtype.itemsize * table.shape[1]))
    t = np.ascontiguousarray(table).view(dt).ravel()
    q = np.ascontiguousarray(queries).view(dt).ravel()
    order = np.argsort(t)
    pos = np.searchsorted(t[order], q)
    pos = np.clip(pos, 0, len(t) - 1)
    hit = t[order[pos]] == q
    return np.where(hit, order[pos], -1)


def _build_rulebook(idx, spatial, ksize, pads, dils, strs, subm):
    """idx: [nnz, 4] (batch, z, y, x) host ints.  Returns (pairs_in,
    pairs_out, pairs_off, out_idx, out_spatial)."""
    key = (idx.tobytes(), idx.shape, tuple(spatial), tuple(ksize),
           tuple(pads), tuple(dils), tuple(strs), bool(subm))
    hit = _RULEBOOK_CACHE.get(key)
    if hit is not None:
        return hit
    idx = np.asarray(idx)
    pads_a, dils_a, strs_a = map(np.asarray, (pads, dils, strs))
    if subm:
        out_spatial = tuple(spatial)
    else:
        out_spatial = tuple(
            (spatial[d] + 2 * pads_a[d] - dils_a[d] * (ksize[d] - 1) - 1)
            // strs_a[d] + 1 for d in range(3))
    cand_in, cand_coord, cand_off = [], [], []
    oid = 0
    for oz in range(ksize[0]):
        for oy in range(ksize[1]):
            for ox in range(ksize[2]):
                off = np.array([oz, oy, ox])
                num = idx[:, 1:] + pads_a - off * dils_a
                ok = (num % strs_a == 0).all(axis=1)
                out_sp = num // strs_a
                ok &= (out_sp >= 0).all(axis=1)
                ok &= (out_sp < np.asarray(out_spatial)).all(axis=1)
                ii = np.nonzero(ok)[0]
                cand_in.append(ii)
                cand_coord.append(
                    np.concatenate([idx[ii, :1], out_sp[ii]], axis=1))
                cand_off.append(np.full(len(ii), oid, np.int64))
                oid += 1
    pin = np.concatenate(cand_in) if cand_in else np.zeros(0, np.int64)
    coords = np.concatenate(cand_coord) if cand_coord else \
        np.zeros((0, 4), np.int64)
    poff = np.concatenate(cand_off) if cand_off else np.zeros(0, np.int64)
    if subm:
        pout = _match_rows(idx, coords)
        keep = pout >= 0
        pin, pout, poff = pin[keep], pout[keep], poff[keep]
        out_idx = idx
    elif len(coords):
        out_idx, pout = np.unique(coords, axis=0, return_inverse=True)
    else:
        out_idx = np.zeros((0, 4), np.int64)
        pout = np.zeros(0, np.int64)
    result = (pin.astype(np.int64), np.asarray(pout, np.int64).ravel(),
              poff, np.asarray(out_idx, np.int64).reshape(-1, 4),
              out_spatial)
    if len(_RULEBOOK_CACHE) > 64:
        _RULEBOOK_CACHE.clear()
    _RULEBOOK_CACHE[key] = result
    return result


def _check_conv_args(data_format, groups=1, ceil_mode=False):
    if data_format != "NDHWC":
        raise NotImplementedError(
            f"sparse conv/pool supports data_format='NDHWC' only "
            f"(got {data_format!r}); permute with sparse.transpose")
    if groups != 1:
        raise NotImplementedError("sparse conv3d groups>1")
    if ceil_mode:
        raise NotImplementedError("sparse max_pool3d ceil_mode=True")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None, subm=False):
    """Sparse 3-D convolution over COO input [N, D, H, W, C]
    (sparse_ops.yaml conv3d:83; kernels phi/kernels/sparse/conv.h).
    `subm=True` is the submanifold variant (output sparsity == input
    sparsity).  Rulebook on host, value compute through apply_op so
    input-value, `weight` and `bias` gradients all flow."""
    _check_conv_args(data_format, groups)
    x = _coerce_coo(x)
    kshape = tuple(int(s) for s in (_val(weight)).shape)  # [kd,kh,kw,Ci,Co]
    kd, kh, kw, ci, co = kshape
    pin, pout, poff, out_idx, out_spatial = _build_rulebook(
        np.asarray(x._bcoo.indices), tuple(x.shape[1:4]), (kd, kh, kw),
        _to3(padding), _to3(dilation), _to3(stride), subm)
    n_out = len(out_idx)
    pin_j, pout_j, poff_j = map(jnp.asarray, (pin, pout, poff))
    weight = weight if isinstance(weight, Tensor) else \
        Tensor(jnp.asarray(_val(weight)), _internal=True)

    def compute(vals, w, b):
        w2 = w.reshape(kd * kh * kw, ci, co)
        contrib = jnp.einsum("pi,pio->po", vals[pin_j], w2[poff_j])
        out = jax.ops.segment_sum(contrib, pout_j, num_segments=n_out)
        if b is not None:
            out = out + b
        return out

    out_t = _apply(compute, "sparse_conv3d", (x.values(), weight, bias))
    shape = (x.shape[0], *out_spatial, co)
    return SparseCooTensor._make(out_t, out_idx, shape)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", name=None):
    return conv3d(x, weight, bias, stride, padding, dilation, groups,
                  data_format, name, subm=True)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """Sparse max pooling over COO input (sparse_ops.yaml maxpool:349;
    phi/kernels/sparse/pool.h): max over each output site's contributing
    input sites, per channel — implicit zeros never participate."""
    _check_conv_args(data_format, ceil_mode=ceil_mode)
    x = _coerce_coo(x)
    ks = _to3(kernel_size)
    st = _to3(stride if stride is not None else kernel_size)
    pin, pout, poff, out_idx, out_spatial = _build_rulebook(
        np.asarray(x._bcoo.indices), tuple(x.shape[1:4]), ks,
        _to3(padding), (1, 1, 1), st, subm=False)
    n_out = len(out_idx)
    pin_j, pout_j = jnp.asarray(pin), jnp.asarray(pout)
    out_t = _apply(
        lambda v: jax.ops.segment_max(v[pin_j], pout_j, num_segments=n_out),
        "sparse_max_pool3d", (x.values(),))
    shape = (x.shape[0], *out_spatial, x.shape[-1])
    return SparseCooTensor._make(out_t, out_idx, shape)


def fused_attention(query, key, value, sparse_mask, key_padding_mask=None,
                    attn_mask=None, name=None):
    """sparse_ops.yaml fused_attention:319 (fused_attention_csr kernel):
    scores computed ONLY at sparse_mask's nonzero positions (SDDMM), sparse
    row softmax, then SpMM with value.  q/k/v: [B, nh, M, hd] dense;
    sparse_mask: [B*nh, M, M] sparse COO, or a 2-D [M, M] mask broadcast
    over every batch-head.  Returns dense out [B, nh, M, hd].  Mask indices
    are static; the value compute runs through apply_op so q/k/v gradients
    flow."""
    qv = _val(query)
    b, nh, m, hd = qv.shape
    mask = sparse_mask
    if isinstance(mask, SparseCsrTensor):
        mask = mask.to_sparse_coo()
    midx = np.asarray(mask._bcoo.indices)
    if midx.shape[1] == 2:
        # 2-D [M, M] mask: broadcast the same pattern to every batch-head
        nnz = len(midx)
        midx = np.concatenate([
            np.repeat(np.arange(b * nh), nnz)[:, None],
            np.tile(midx, (b * nh, 1))], axis=1)
    bh_np, row_np, col_np = midx[:, 0], midx[:, 1], midx[:, 2]
    seg_np = bh_np * m + row_np
    bh, row, col, seg = map(jnp.asarray, (bh_np, row_np, col_np, seg_np))
    nseg = b * nh * m

    def compute(q, k, v, kpm, am):
        qf = q.reshape(b * nh, m, hd)
        kf = k.reshape(b * nh, m, hd)
        vf = v.reshape(b * nh, m, hd)
        scores = jnp.einsum("ph,ph->p", qf[bh, row], kf[bh, col]) \
            / jnp.sqrt(jnp.asarray(hd, qf.dtype))
        if kpm is not None:   # [B, M] additive mask keyed by key position
            scores = scores + kpm.reshape(b, m)[bh // nh, col]
        if am is not None:    # [M, M] additive
            scores = scores + am[row, col]
        smax = jax.ops.segment_max(scores, seg, num_segments=nseg)
        e = jnp.exp(scores - smax[seg])
        ssum = jax.ops.segment_sum(e, seg, num_segments=nseg)
        p = e / jnp.maximum(ssum[seg], 1e-38)
        out = jax.ops.segment_sum(p[:, None] * vf[bh, col], seg,
                                  num_segments=nseg)
        return out.reshape(b, nh, m, hd)

    return _apply(compute, "sparse_fused_attention",
                  (query, key, value, key_padding_mask, attn_mask))


# -- paddle.sparse.nn --------------------------------------------------------

from ..nn.layer_base import Layer as _Layer  # noqa: E402


class Conv3D(_Layer):
    """paddle.sparse.nn.Conv3D (reference incubate/sparse/nn/layer/conv.py):
    kernel [kd, kh, kw, Ci, Co] parameter over sparse NDHWC input."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 data_format="NDHWC", weight_attr=None, bias_attr=None):
        super().__init__()
        kd, kh, kw = _to3(kernel_size)
        self.weight = self.create_parameter(
            [kd, kh, kw, in_channels, out_channels], attr=weight_attr)
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True)
        self._args = (stride, padding, dilation, groups, subm, data_format)

    def forward(self, x):
        stride, padding, dilation, groups, subm, fmt = self._args
        return conv3d(x, self.weight, self.bias, stride, padding,
                      dilation, groups, data_format=fmt, subm=subm)


class SubmConv3D(Conv3D):
    def __init__(self, *args, **kwargs):
        kwargs["subm"] = True
        super().__init__(*args, **kwargs)


class MaxPool3D:
    def __init__(self, kernel_size, stride=None, padding=0):
        self._args = (kernel_size, stride, padding)

    def __call__(self, x):
        return max_pool3d(x, *self._args)


class _ReLULayer:
    def __call__(self, x):
        return relu(x)


class nn:
    """paddle.sparse.nn subset."""
    ReLU = _ReLULayer
    Conv3D = Conv3D
    SubmConv3D = SubmConv3D
    MaxPool3D = MaxPool3D
    functional = type("functional", (), {
        "relu": staticmethod(relu),
        "conv3d": staticmethod(conv3d),
        "subm_conv3d": staticmethod(subm_conv3d),
        "max_pool3d": staticmethod(max_pool3d),
        "attention": staticmethod(fused_attention),
        "softmax": staticmethod(softmax),
    })
