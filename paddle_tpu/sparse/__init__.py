"""paddle.sparse parity (python/paddle/incubate/sparse → paddle.sparse):
SparseCooTensor/SparseCsrTensor (phi/core sparse_coo_tensor.h /
sparse_csr_tensor.h analogs) over jax.experimental.sparse BCOO.

The reference keeps a dedicated sparse kernel tree (phi/kernels/sparse/, 29
files); XLA's sparse support is BCOO-based, so COO is the native layout here
and CSR is a view-style wrapper that converts through COO.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "add", "multiply", "matmul", "masked_matmul",
           "relu", "transpose", "is_same_shape"]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor (dense_tensor.h's SparseCooTensor analog)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- paddle surface ------------------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T, _internal=True)  # [ndim, nnz]

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data, _internal=True)

    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense(), _internal=True)

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor.from_coo(self)

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR view (crows/cols/values surface); stored as COO underneath."""

    def __init__(self, crows, cols, values, shape):
        self._crows = jnp.asarray(_val(crows), jnp.int64)
        self._cols = jnp.asarray(_val(cols), jnp.int64)
        self._values = _val(values)
        self._shape = tuple(int(s) for s in shape)

    @classmethod
    def from_coo(cls, coo: SparseCooTensor):
        if len(coo.shape) != 2:
            raise ValueError(
                f"CSR conversion supports 2-D tensors, got shape "
                f"{coo.shape}; keep batched sparse data in COO")
        coo = coo.coalesce()
        idx = np.asarray(coo._bcoo.indices)
        vals = coo._bcoo.data
        rows, cols = idx[:, 0], idx[:, 1]
        order = np.lexsort((cols, rows))
        rows, cols = rows[order], cols[order]
        vals = vals[jnp.asarray(order)]
        n_rows = coo.shape[0]
        crows = np.zeros(n_rows + 1, np.int64)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows)
        return cls(crows, cols, vals, coo.shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    def crows(self) -> Tensor:
        return Tensor(self._crows, _internal=True)

    def cols(self) -> Tensor:
        return Tensor(self._cols, _internal=True)

    def values(self) -> Tensor:
        return Tensor(self._values, _internal=True)

    def nnz(self) -> int:
        return int(self._cols.shape[0])

    def to_sparse_coo(self, sparse_dim=2) -> SparseCooTensor:
        crows = np.asarray(self._crows)
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        idx = jnp.stack([jnp.asarray(rows),
                         jnp.asarray(self._cols)], axis=1)
        bcoo = jsparse.BCOO((self._values, idx), shape=self._shape)
        return SparseCooTensor(bcoo)

    def to_dense(self) -> Tensor:
        return self.to_sparse_coo().to_dense()

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


# -- constructors ------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    idx = jnp.asarray(_val(indices), jnp.int64)
    vals = _val(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    if idx.ndim != 2:
        raise ValueError("indices must be [sparse_dim, nnz]")
    if shape is None:
        shape = tuple(int(i) for i in np.asarray(idx.max(axis=1)) + 1)
    bcoo = jsparse.BCOO((vals, idx.T), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    vals = _val(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    return SparseCsrTensor(crows, cols, vals, shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


# -- ops (phi/kernels/sparse parity subset) ----------------------------------

def _coerce_coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def add(x, y, name=None):
    x, y = _coerce_coo(x), _coerce_coo(y)
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        idx = jnp.concatenate([x._bcoo.indices, y._bcoo.indices], axis=0)
        data = jnp.concatenate([x._bcoo.data, y._bcoo.data], axis=0)
        out = jsparse.BCOO((data, idx), shape=x._bcoo.shape).sum_duplicates()
        return SparseCooTensor(out)
    dense = _val(y if isinstance(x, SparseCooTensor) else x)
    sp = x if isinstance(x, SparseCooTensor) else y
    return Tensor(sp._bcoo.todense() + dense, _internal=True)


def multiply(x, y, name=None):
    x = _coerce_coo(x)
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        y = _coerce_coo(y).to_dense()
    yv = _val(y)
    # elementwise multiply only touches stored values
    gathered = yv[tuple(x._bcoo.indices[:, d]
                        for d in range(x._bcoo.indices.shape[1]))] \
        if yv.ndim else yv
    return SparseCooTensor(jsparse.BCOO((x._bcoo.data * gathered,
                                         x._bcoo.indices),
                                        shape=x._bcoo.shape))


def matmul(x, y, name=None):
    """sparse @ dense → dense (phi sparse matmul kernels)."""
    x = _coerce_coo(x)
    yv = _val(y)
    out = x._bcoo @ yv
    return Tensor(out, _internal=True)


def masked_matmul(x, y, mask, name=None):
    """dense @ dense sampled at mask's sparsity (SDDMM)."""
    xv, yv = _val(x), _val(y)
    mask = _coerce_coo(mask)
    idx = mask._bcoo.indices
    rows, cols = idx[:, 0], idx[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows, :], yv[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape))


def relu(x, name=None):
    x = _coerce_coo(x)
    return SparseCooTensor(jsparse.BCOO((jnp.maximum(x._bcoo.data, 0),
                                         x._bcoo.indices),
                                        shape=x._bcoo.shape))


def transpose(x, perm, name=None):
    x = _coerce_coo(x)
    idx = x._bcoo.indices[:, jnp.asarray(perm)]
    shape = tuple(x._bcoo.shape[p] for p in perm)
    return SparseCooTensor(jsparse.BCOO((x._bcoo.data, idx), shape=shape))


class nn:
    """paddle.sparse.nn subset: ReLU layer."""

    class ReLU:
        def __call__(self, x):
            return relu(x)


# -- value-wise unary family (sparse_ops.yaml: abs/sin/.../sqrt applied to
# stored values only, zero-preserving by construction) ------------------------

def _valuewise(fn):
    def op(x, name=None):
        x = _coerce_coo(x)
        return SparseCooTensor(jsparse.BCOO((fn(x._bcoo.data),
                                             x._bcoo.indices),
                                            shape=x._bcoo.shape))
    return op


abs = _valuewise(jnp.abs)          # noqa: A001
sin = _valuewise(jnp.sin)
sinh = _valuewise(jnp.sinh)
asin = _valuewise(jnp.arcsin)
asinh = _valuewise(jnp.arcsinh)
tan = _valuewise(jnp.tan)
tanh = _valuewise(jnp.tanh)
atan = _valuewise(jnp.arctan)
atanh = _valuewise(jnp.arctanh)
sqrt = _valuewise(jnp.sqrt)
square = _valuewise(jnp.square)
log1p = _valuewise(jnp.log1p)
expm1 = _valuewise(jnp.expm1)
relu6 = _valuewise(lambda v: jnp.clip(v, 0, 6))


def leaky_relu(x, negative_slope=0.01, name=None):
    return _valuewise(lambda v: jnp.where(v > 0, v,
                                          negative_slope * v))(x)


def pow(x, factor, name=None):  # noqa: A001
    return _valuewise(lambda v: v ** factor)(x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, name=None):
    # bias on a sparse tensor only touches stored values (yaml scale op)
    return _valuewise(lambda v: v * scale + bias if bias_after_scale
                      else (v + bias) * scale)(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    x = _coerce_coo(x)
    idx = x._bcoo.indices.astype(index_dtype) if index_dtype else \
        x._bcoo.indices
    data = x._bcoo.data.astype(value_dtype) if value_dtype else x._bcoo.data
    return SparseCooTensor(jsparse.BCOO((data, idx), shape=x._bcoo.shape))


def subtract(x, y, name=None):
    return add(x, scale(_coerce_coo(y), -1.0)
               if isinstance(y, (SparseCooTensor, SparseCsrTensor))
               else Tensor(-_val(y), _internal=True))


def divide(x, y, name=None):
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        raise ValueError("sparse/sparse divide is undefined off the "
                         "intersection; densify first")
    return multiply(x, Tensor(1.0 / _val(y), _internal=True))


def divide_scalar(x, scalar, name=None):
    return _valuewise(lambda v: v / scalar)(x)


def mv(x, vec, name=None):
    """sparse matrix @ dense vector (sparse_ops.yaml mv)."""
    x = _coerce_coo(x)
    return Tensor(x._bcoo @ _val(vec), _internal=True)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    """beta*input + alpha*(sparse x @ dense y)."""
    x = _coerce_coo(x)
    return Tensor(beta * _val(input) + alpha * (x._bcoo @ _val(y)),
                  _internal=True)


def softmax(x, axis=-1, name=None):
    """Row-wise softmax over stored values only (phi sparse softmax:
    implicit zeros do NOT participate) — one segment_max/segment_sum pass
    over the CSR values, O(1) device dispatches regardless of row count."""
    if axis not in (-1, 1):
        raise ValueError("sparse softmax supports the last axis only")
    csr = SparseCsrTensor.from_coo(_coerce_coo(x)) \
        if isinstance(x, SparseCooTensor) else x
    import numpy as _np
    crows = _np.asarray(csr._crows)
    counts = _np.diff(crows)
    row_ids = jnp.asarray(_np.repeat(_np.arange(len(counts)), counts))
    vals = csr._values
    nrows = len(counts)
    row_max = jax.ops.segment_max(vals, row_ids, num_segments=nrows)
    e = jnp.exp(vals - row_max[row_ids])
    row_sum = jax.ops.segment_sum(e, row_ids, num_segments=nrows)
    out = e / row_sum[row_ids]
    return SparseCsrTensor(csr._crows, csr._cols, out, csr.shape)
