// TCPStore — native KV rendezvous store.
//
// Parity target: paddle/fluid/distributed/store/tcp_store.h:120 (the C++
// TCPStore behind python/paddle/distributed/parallel.py:248) and its socket
// layer tcp_utils.cc.  Re-implemented for the TPU build: a single poll()-loop
// server thread with a mutex-guarded map, plus a blocking client.  Exposed as
// a C ABI for ctypes (no pybind11 in this image).
//
// Protocol (little-endian):
//   request : u8 op | u32 klen | key | [u32 vlen | val] | [i64 delta]
//   ops     : 1=SET 2=GET 3=ADD 4=DEL 5=NUMKEYS
//   reply   : GET -> u32 vlen (0xFFFFFFFF = missing) | val
//             SET/DEL -> u8 1;  ADD -> i64 new value; NUMKEYS -> i64 count
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint8_t kSet = 1, kGet = 2, kAdd = 3, kDel = 4, kNumKeys = 5;
constexpr uint32_t kMissing = 0xFFFFFFFFu;

bool read_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::thread loop;
  std::atomic<bool> stop{false};
  std::mutex mu;
  std::map<std::string, std::string> kv;

  ~Server() { shutdown(); }

  void shutdown() {
    bool expected = false;
    if (!stop.compare_exchange_strong(expected, true)) return;
    if (listen_fd >= 0) ::shutdown(listen_fd, SHUT_RDWR);
    if (loop.joinable()) loop.join();
    if (listen_fd >= 0) ::close(listen_fd);
    listen_fd = -1;
  }

  bool handle(int fd) {
    uint8_t op;
    if (!read_exact(fd, &op, 1)) return false;
    uint32_t klen;
    if (!read_exact(fd, &klen, 4) || klen > (1u << 20)) return false;
    std::string key(klen, '\0');
    if (!read_exact(fd, key.data(), klen)) return false;

    switch (op) {
      case kSet: {
        uint32_t vlen;
        if (!read_exact(fd, &vlen, 4) || vlen > (1u << 28)) return false;
        std::string val(vlen, '\0');
        if (!read_exact(fd, val.data(), vlen)) return false;
        {
          std::lock_guard<std::mutex> g(mu);
          kv[key] = std::move(val);
        }
        uint8_t ok = 1;
        return write_exact(fd, &ok, 1);
      }
      case kGet: {
        std::string val;
        bool found;
        {
          std::lock_guard<std::mutex> g(mu);
          auto it = kv.find(key);
          found = it != kv.end();
          if (found) val = it->second;
        }
        uint32_t vlen = found ? static_cast<uint32_t>(val.size()) : kMissing;
        if (!write_exact(fd, &vlen, 4)) return false;
        if (found && !val.empty() &&
            !write_exact(fd, val.data(), val.size()))
          return false;
        return true;
      }
      case kAdd: {
        int64_t delta;
        if (!read_exact(fd, &delta, 8)) return false;
        int64_t cur = 0;
        {
          std::lock_guard<std::mutex> g(mu);
          auto it = kv.find(key);
          if (it != kv.end() && it->second.size() == 8)
            std::memcpy(&cur, it->second.data(), 8);
          cur += delta;
          std::string val(8, '\0');
          std::memcpy(val.data(), &cur, 8);
          kv[key] = std::move(val);
        }
        return write_exact(fd, &cur, 8);
      }
      case kDel: {
        {
          std::lock_guard<std::mutex> g(mu);
          kv.erase(key);
        }
        uint8_t ok = 1;
        return write_exact(fd, &ok, 1);
      }
      case kNumKeys: {
        int64_t n;
        {
          std::lock_guard<std::mutex> g(mu);
          n = static_cast<int64_t>(kv.size());
        }
        return write_exact(fd, &n, 8);
      }
      default:
        return false;
    }
  }

  void run() {
    std::vector<struct pollfd> fds;
    fds.push_back({listen_fd, POLLIN, 0});
    while (!stop.load()) {
      int rc = ::poll(fds.data(), fds.size(), 200 /*ms*/);
      if (rc < 0) {
        if (errno == EINTR) continue;
        break;
      }
      if (rc == 0) continue;
      // accept new connections
      if (fds[0].revents & POLLIN) {
        int cfd = ::accept(listen_fd, nullptr, nullptr);
        if (cfd >= 0) {
          int one = 1;
          ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          fds.push_back({cfd, POLLIN, 0});
        }
      }
      for (size_t i = fds.size(); i-- > 1;) {
        if (fds[i].revents & (POLLIN | POLLHUP | POLLERR)) {
          if (!(fds[i].revents & POLLIN) || !handle(fds[i].fd)) {
            ::close(fds[i].fd);
            fds.erase(fds.begin() + static_cast<long>(i));
          }
        }
      }
    }
    for (size_t i = 1; i < fds.size(); ++i) ::close(fds[i].fd);
  }
};

struct Client {
  int fd = -1;
  std::mutex mu;  // one request/response at a time per client

  ~Client() {
    if (fd >= 0) ::close(fd);
  }
};

}  // namespace

extern "C" {

// returns opaque handle or null; port 0 picks a free port (query with
// tcpstore_server_port)
void* tcpstore_server_start(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = host && *host ? ::inet_addr(host) : INADDR_ANY;
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  auto* s = new Server();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->loop = std::thread([s] { s->run(); });
  return s;
}

int tcpstore_server_port(void* h) { return static_cast<Server*>(h)->port; }

void tcpstore_server_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  s->shutdown();
  delete s;
}

void* tcpstore_client_connect(const char* host, int port, int timeout_ms) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  std::snprintf(portstr, sizeof(portstr), "%d", port);
  if (::getaddrinfo(host, portstr, &hints, &res) != 0 || !res) return nullptr;
  int fd = -1;
  // retry until the server is up or the deadline passes (rendezvous races)
  for (int waited = 0; waited <= timeout_ms; waited += 100) {
    fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd >= 0 && ::connect(fd, res->ai_addr, res->ai_addrlen) == 0) break;
    if (fd >= 0) ::close(fd);
    fd = -1;
    ::usleep(100 * 1000);
  }
  ::freeaddrinfo(res);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Client();
  c->fd = fd;
  return c;
}

void tcpstore_client_free(void* h) { delete static_cast<Client*>(h); }

static bool send_key(int fd, uint8_t op, const char* key, uint32_t klen) {
  return write_exact(fd, &op, 1) && write_exact(fd, &klen, 4) &&
         write_exact(fd, key, klen);
}

int tcpstore_set(void* h, const char* key, const char* val, int vlen) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  uint32_t v = static_cast<uint32_t>(vlen);
  if (!send_key(c->fd, kSet, key, std::strlen(key))) return -1;
  if (!write_exact(c->fd, &v, 4)) return -1;
  if (vlen > 0 && !write_exact(c->fd, val, v)) return -1;
  uint8_t ok;
  return read_exact(c->fd, &ok, 1) && ok == 1 ? 0 : -1;
}

// returns length, -1 = missing, -2 = error; caller buffer must hold cap bytes
int tcpstore_get(void* h, const char* key, char* buf, int cap) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  if (!send_key(c->fd, kGet, key, std::strlen(key))) return -2;
  uint32_t vlen;
  if (!read_exact(c->fd, &vlen, 4)) return -2;
  if (vlen == kMissing) return -1;
  if (vlen > static_cast<uint32_t>(cap)) {
    // drain to keep the stream aligned, then report under-capacity
    std::vector<char> tmp(vlen);
    read_exact(c->fd, tmp.data(), vlen);
    return -3;
  }
  if (vlen > 0 && !read_exact(c->fd, buf, vlen)) return -2;
  return static_cast<int>(vlen);
}

long long tcpstore_add(void* h, const char* key, long long delta) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  int64_t d = delta, out = 0;
  if (!send_key(c->fd, kAdd, key, std::strlen(key))) return -1;
  if (!write_exact(c->fd, &d, 8)) return -1;
  if (!read_exact(c->fd, &out, 8)) return -1;
  return out;
}

int tcpstore_delete(void* h, const char* key) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  if (!send_key(c->fd, kDel, key, std::strlen(key))) return -1;
  uint8_t ok;
  return read_exact(c->fd, &ok, 1) && ok == 1 ? 0 : -1;
}

long long tcpstore_num_keys(void* h) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  if (!send_key(c->fd, kNumKeys, "", 0)) return -1;
  int64_t out = 0;
  if (!read_exact(c->fd, &out, 8)) return -1;
  return out;
}

}  // extern "C"
