// paddle_ext.h — the custom-operator ABI for paddle_tpu's cpp_extension
// (parity target: paddle/fluid/framework/custom_operator.cc PD_BUILD_OP +
// utils/cpp_extension; the plugin-facing struct mirrors the spirit of
// phi/backends/custom/device_ext.h's C tables, SURVEY §2.1).
//
// A custom op is an exported C function named  pt_op_<name>  with the
// signature below.  Tensors are host buffers: custom C++ runs on the host
// CPU (the TPU compute path is XLA/Pallas); the framework bridges it into
// jitted programs via a host callback.
#pragma once
#include <cstdint>

extern "C" {

typedef struct {
  void* data;           // contiguous buffer
  const int64_t* shape; // dims
  int ndim;
  int dtype;            // 0=f32 1=f64 2=i32 3=i64 4=u8 5=bool
} PT_Tensor;

// return 0 on success; nonzero aborts the op with an error
typedef int (*PT_OpFn)(const PT_Tensor* inputs, int n_inputs,
                       PT_Tensor* outputs, int n_outputs);

}  // extern "C"

// convenience: declare an op with the canonical exported name
#define PT_BUILD_OP(name)                                            \
  extern "C" int pt_op_##name(const PT_Tensor* inputs, int n_inputs, \
                              PT_Tensor* outputs, int n_outputs)
