// Shared-memory ring buffer — the native transport for multi-process
// DataLoader workers.
//
// Parity target: the reference moves worker-produced LoDTensors through
// shared memory instead of pickling them over pipes
// (python/paddle/fluid/dataloader/dataloader_iter.py:342
// `_DataLoaderIterMultiProcess` + core._array_to_share_memory_tensor; the
// C++ double-buffer side is operators/reader/buffered_reader.cc).  Here one
// POSIX shm segment holds a byte ring with a process-shared mutex/condvar
// pair; workers write length-prefixed batches, the parent reads them without
// any serialization layer in between.  Exposed as a C ABI for ctypes.
#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cstdint>
#include <string>

namespace {

struct Header {
  pthread_mutex_t mu;
  pthread_cond_t can_read;
  pthread_cond_t can_write;
  uint64_t capacity;   // ring payload capacity in bytes
  uint64_t head;       // read offset
  uint64_t tail;       // write offset
  uint64_t used;       // bytes in ring
  uint32_t closed;
};

struct Handle {
  Header* h;
  uint8_t* data;
  uint64_t capacity;
  std::string name;
  bool owner;
};

void ring_copy_in(Handle* hd, const uint8_t* src, uint64_t n) {
  Header* h = hd->h;
  uint64_t tail = h->tail;
  uint64_t first = std::min(n, h->capacity - tail);
  memcpy(hd->data + tail, src, first);
  if (n > first) memcpy(hd->data, src + first, n - first);
  h->tail = (tail + n) % h->capacity;
  h->used += n;
}

void ring_copy_out(Handle* hd, uint8_t* dst, uint64_t n) {
  Header* h = hd->h;
  uint64_t head = h->head;
  uint64_t first = std::min(n, h->capacity - head);
  memcpy(dst, hd->data + head, first);
  if (n > first) memcpy(dst + first, hd->data, n - first);
  h->head = (head + n) % h->capacity;
  h->used -= n;
}

struct timespec deadline_from_ms(int timeout_ms) {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) {
    ts.tv_sec += 1;
    ts.tv_nsec -= 1000000000L;
  }
  return ts;
}

// absolute deadline so repeated wakeups can't extend the timeout
int wait_until(pthread_cond_t* cv, pthread_mutex_t* mu, int timeout_ms,
               const struct timespec* deadline) {
  if (timeout_ms < 0) return pthread_cond_wait(cv, mu);
  return pthread_cond_timedwait(cv, mu, deadline);
}

// closed states: 0 = open, 1 = graceful close (readers may drain),
// 2 = poisoned (byte-state untrustworthy — nobody drains)
constexpr uint32_t kClosed = 1, kPoisoned = 2;

// a peer died holding the lock: the ring byte-state (length prefixes,
// head/tail/used) can no longer be trusted
void poison(Header* h) {
  pthread_mutex_consistent(&h->mu);
  h->closed = kPoisoned;
  pthread_cond_broadcast(&h->can_read);
  pthread_cond_broadcast(&h->can_write);
}

}  // namespace

extern "C" {

// linger=0: the name is unlinked immediately after mmap, so the segment
// lives exactly as long as the mappings (fork-inherited) and can never leak
// into /dev/shm after a crash.  linger=1 keeps the name for shmring_open
// peers; the creator must call shmring_free.
void* shmring_create(const char* name, uint64_t capacity, int linger) {
  size_t total = sizeof(Header) + capacity;
  ::shm_unlink(name);  // stale segment from a crashed run
  int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (::ftruncate(fd, static_cast<off_t>(total)) != 0) {
    ::close(fd);
    ::shm_unlink(name);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd,
                     0);
  ::close(fd);
  if (mem == MAP_FAILED) {
    ::shm_unlink(name);
    return nullptr;
  }
  if (!linger) ::shm_unlink(name);
  auto* h = static_cast<Header*>(mem);
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->can_read, &ca);
  pthread_cond_init(&h->can_write, &ca);
  h->capacity = capacity;
  h->head = h->tail = h->used = 0;
  h->closed = 0;
  auto* hd = new Handle{h, reinterpret_cast<uint8_t*>(h + 1), capacity, name,
                        linger != 0};
  return hd;
}

void* shmring_open(const char* name) {
  int fd = ::shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, static_cast<size_t>(st.st_size),
                     PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* h = static_cast<Header*>(mem);
  auto* hd = new Handle{h, reinterpret_cast<uint8_t*>(h + 1), h->capacity,
                        name, false};
  return hd;
}

// returns 0 when the lock is held and the ring is trustworthy; -1 after a
// peer died holding it (ring is poisoned, caller must bail but the mutex IS
// held when -1 from EOWNERDEAD... so callers unlock)
static int lock_robust(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    poison(h);
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  return rc == 0 ? 0 : -1;
}

// write one message (length-prefixed); blocks while the ring is full.
// returns 0 ok, -1 closed/error, -2 timeout, -3 message larger than ring
int shmring_write(void* vh, const void* buf, uint64_t n, int timeout_ms) {
  auto* hd = static_cast<Handle*>(vh);
  Header* h = hd->h;
  uint64_t need = n + 8;
  if (need > h->capacity) return -3;
  if (lock_robust(h) != 0) return -1;
  struct timespec dl = deadline_from_ms(timeout_ms < 0 ? 0 : timeout_ms);
  while (!h->closed && h->capacity - h->used < need) {
    int rc = wait_until(&h->can_write, &h->mu, timeout_ms, &dl);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
    if (rc == EOWNERDEAD) {
      poison(h);
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  if (h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  uint64_t len = n;
  ring_copy_in(hd, reinterpret_cast<uint8_t*>(&len), 8);
  ring_copy_in(hd, static_cast<const uint8_t*>(buf), n);
  pthread_cond_signal(&h->can_read);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// read one message into buf (cap bytes). returns message length, -1 closed,
// -2 timeout, -3 under-capacity (message length returned via *need_out,
// message stays queued)
long long shmring_read(void* vh, void* buf, uint64_t cap, int timeout_ms,
                       uint64_t* need_out) {
  auto* hd = static_cast<Handle*>(vh);
  Header* h = hd->h;
  if (lock_robust(h) != 0) return -1;
  struct timespec dl = deadline_from_ms(timeout_ms < 0 ? 0 : timeout_ms);
  while (!h->closed && h->used < 8) {
    int rc = wait_until(&h->can_read, &h->mu, timeout_ms, &dl);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
    if (rc == EOWNERDEAD) {
      poison(h);
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  if (h->closed == kPoisoned || h->used < 8) {
    // poisoned bytes must never be drained; graceful close drains
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  // peek the length without consuming
  uint64_t len = 0;
  uint64_t head = h->head;
  uint64_t first = std::min<uint64_t>(8, h->capacity - head);
  memcpy(&len, hd->data + head, first);
  if (first < 8)
    memcpy(reinterpret_cast<uint8_t*>(&len) + first, hd->data, 8 - first);
  if (len > cap) {
    if (need_out) *need_out = len;
    pthread_mutex_unlock(&h->mu);
    return -3;
  }
  // consume header + payload
  h->head = (head + 8) % h->capacity;
  h->used -= 8;
  ring_copy_out(hd, static_cast<uint8_t*>(buf), len);
  pthread_cond_signal(&h->can_write);
  pthread_mutex_unlock(&h->mu);
  return static_cast<long long>(len);
}

void shmring_close(void* vh) {
  auto* hd = static_cast<Handle*>(vh);
  Header* h = hd->h;
  if (lock_robust(h) == 0) {
    if (h->closed == 0) h->closed = kClosed;  // never mask a poisoned state
    pthread_cond_broadcast(&h->can_read);
    pthread_cond_broadcast(&h->can_write);
    pthread_mutex_unlock(&h->mu);
  }
}

void shmring_free(void* vh) {
  auto* hd = static_cast<Handle*>(vh);
  size_t total = sizeof(Header) + hd->capacity;
  bool owner = hd->owner;
  std::string name = hd->name;
  ::munmap(hd->h, total);
  if (owner) ::shm_unlink(name.c_str());
  delete hd;
}

}  // extern "C"
