"""paddle.version parity (generated at build time in the reference,
cmake/version.cmake)."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
istaged = False
commit = "unknown"
with_gpu = "OFF"
with_tpu = "ON"
cuda_version = "False"
cudnn_version = "False"


def show():
    print(f"paddle-tpu {full_version} (tpu-native, jax/xla/pallas backend)")


def cuda():
    return False


def tpu():
    return True
