"""auto_cast — O1/O2 mixed precision (reference: python/paddle/amp/auto_cast.py).

Implemented as a thread-local autocast state consulted by the defop layer:
inside an ``auto_cast(True)`` scope, ops on the white list compute in the low
dtype (bf16 by default on TPU), black-list ops compute in fp32.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..core.dtype import to_jax

# reference white/black lists (amp/auto_cast.py WHITE_LIST/BLACK_LIST)
white_list = {"matmul", "mm", "bmm", "mv", "conv1d", "conv2d", "conv3d",
              "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
              "linear", "einsum", "attention", "scaled_dot_product_attention",
              "resnet_stem_s2d", "sparse_conv3d", "sparse_fused_attention"}
black_list = {"exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
              "log_softmax", "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
              "cross_entropy", "fused_nll_loss", "layer_norm", "batch_norm",
              "reduce_sum", "pow"}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def amp_state():
    return _state


def should_cast(op_name: str) -> str | None:
    """Return 'low'/'high'/None for an op under the active autocast scope."""
    if not _state.enabled:
        return None
    if op_name in _state.custom_black or op_name in black_list:
        return "high"
    if _state.level == "O2":
        return "low"
    if op_name in _state.custom_white or op_name in white_list:
        return "low"
    return None


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    prev = (_state.enabled, _state.dtype, _state.level, _state.custom_white,
            _state.custom_black)
    _state.enabled = bool(enable)
    _state.dtype = jnp.dtype(to_jax(dtype))
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.custom_white,
         _state.custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration: cast model params to the AMP dtype (reference
    amp/auto_cast.py:81 `decorate`).  Master fp32 weights live in the optimizer
    functional state, so params can safely be low precision."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers
