"""GradScaler — dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py:26,
check_finite_and_unscale + update_loss_scaling ops).

On TPU with bf16 autocast, scaling is unnecessary; the scaler stays
API-compatible (scale→backward→step→update) and implements true dynamic
scaling for fp16 use."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled: set[int] = set()  # optimizers already unscaled this step

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(np.float32(self._scale))

    def set_init_loss_scaling(self, value):
        self._scale = float(value)

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable or id(optimizer) in self._unscaled:
            return
        self._unscaled.add(id(optimizer))
        inv = 1.0 / self._scale
        finite_count = None
        n = 0
        for p in optimizer._parameter_list():
            if p.grad is not None:
                g = p.grad._value * inv
                ok = jnp.all(jnp.isfinite(g)).astype(jnp.int32)
                finite_count = ok if finite_count is None else finite_count + ok
                n += 1
                p.grad._replace_(g, None)
        # single host sync for the whole parameter set
        self._found_inf = (finite_count is not None and
                           int(finite_count) != n)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()
        self._unscaled.discard(id(optimizer))

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "incr_count": self._good_steps,
                "decr_count": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)


AmpScaler = GradScaler
