"""Automatic mixed precision (reference: python/paddle/amp/auto_cast.py:21,
grad_scaler.py:26).

On TPU the AMP dtype of choice is bfloat16: same exponent range as fp32, so
loss scaling is numerically unnecessary — GradScaler stays API-compatible but
becomes a cheap pass-through when scaling is disabled or dtype is bf16.
O1 = white/black-list op casting at the Tensor-op boundary; O2 = cast the whole
model to the low dtype with fp32 master weights held by the optimizer.
"""
from .auto_cast import auto_cast, decorate, amp_guard, white_list, black_list  # noqa: F401
from .grad_scaler import GradScaler, AmpScaler  # noqa: F401
