"""Mixture-of-Experts with expert parallelism — parity with
incubate/distributed/models/moe (MoELayer at moe_layer.py:244, gates under
gate/, grad clip, and the global_scatter/global_gather dispatch that the
reference implements as CUDA alltoall ops,
paddle/fluid/operators/collective/global_scatter_op.cc).
"""
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa: F401
from .grad_clip import ClipGradForMOEByGlobalNorm  # noqa: F401
from .moe_layer import MoELayer  # noqa: F401
from .utils import (  # noqa: F401
    global_gather,
    global_scatter,
    _limit_by_capacity,
    _number_count,
    _prune_gate_by_capacity,
    _random_routing,
)
