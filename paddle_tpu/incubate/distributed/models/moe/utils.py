"""MoE routing utilities — parity with incubate/distributed/models/moe/utils.py
(`_number_count`, `_limit_by_capacity`, `_prune_gate_by_capacity`,
`_random_routing`, backed in the reference by number_count_op /
limit_by_capacity_op / prune_gate_by_capacity_op CUDA kernels) and the
`global_scatter`/`global_gather` token-exchange collectives
(operators/collective/global_scatter_op.cc, global_gather_op.cc).

TPU-native: the count/limit/prune helpers are O(N·E) one-hot reductions that
XLA fuses; the global exchange is a fixed-capacity `lax.all_to_all` over the
expert mesh axis (static shapes — the variable-length brpc-style exchange the
reference does has no efficient XLA analog, and capacity-based dispatch is the
GShard-standard TPU formulation anyway).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.tensor import Tensor


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _number_count(numbers, upper_range):
    """Count occurrences of each id in [0, upper_range) (number_count_op)."""
    n = _unwrap(numbers).reshape(-1)
    oh = jax.nn.one_hot(n, upper_range, dtype=jnp.int64)
    return Tensor(oh.sum(axis=0), _internal=True)

def _limit_by_capacity(expert_count, capacity, n_worker):
    """Clamp per-expert counts by per-worker capacity (limit_by_capacity_op)."""
    ec = _unwrap(expert_count)
    cap = _unwrap(capacity)
    ec2 = ec.reshape(n_worker, -1) if ec.ndim == 1 else ec
    out = jnp.minimum(ec2, cap[None, :] if cap.ndim == 1 else cap)
    return Tensor(out.reshape(ec.shape), _internal=True)


def _prune_gate_by_capacity(gate_idx, expert_count, n_expert, n_worker):
    """Set gate ids to -1 for tokens beyond their expert's capacity
    (prune_gate_by_capacity_op).  Position of a token within its expert is its
    prefix count among same-expert tokens."""
    idx = _unwrap(gate_idx).reshape(-1)
    counts = _unwrap(expert_count).reshape(-1)
    total = n_expert * n_worker
    oh = jax.nn.one_hot(idx, total, dtype=jnp.int32)
    pos = jnp.cumsum(oh, axis=0) * oh  # 1-based rank within expert
    rank = (pos.sum(axis=1) - 1).astype(jnp.int32)
    cap = counts[jnp.clip(idx, 0, total - 1)]
    keep = (idx >= 0) & (rank < cap)
    return Tensor(jnp.where(keep, idx, -1), _internal=True)


def _random_routing(topk_idx, topk_value, prob, topk=2):
    """GShard 2nd-expert random routing (random_routing_op): keep the second
    expert only with probability proportional to its gate value (drop when
    `prob >= 2 * value`)."""
    if topk != 2:
        raise ValueError("_random_routing supports topk=2 only")
    idx = _unwrap(topk_idx)
    val = _unwrap(topk_value)
    p = _unwrap(prob)
    second = jnp.where(p < 2.0 * val[..., 1], idx[..., 1], -1)
    return Tensor(jnp.stack([idx[..., 0], second], axis=-1), _internal=True)


def global_scatter(x, local_count, global_count, group=None):
    """Token exchange to expert-owner ranks (global_scatter_op.cc).

    Fixed-capacity formulation: `x` is the dispatched tensor
    [n_expert_global, capacity, d_model]; over a bound expert axis this is an
    all_to_all that leaves each rank holding [n_expert_local, world*capacity,
    d_model].  Outside shard_map it is the identity (single worker).
    Differentiable (runs on the eager tape; lax.all_to_all has a VJP).
    """
    from .....core.op import apply_op
    from .....distributed import collective as coll

    g = coll._group(group)
    if not coll._in_trace(g):
        return x if isinstance(x, Tensor) else Tensor(_unwrap(x),
                                                      _internal=True)
    axis = g.axis_name
    t = x if isinstance(x, Tensor) else Tensor(_unwrap(x), _internal=True)
    return apply_op(
        lambda v: jax.lax.all_to_all(v, axis, split_axis=0, concat_axis=1,
                                     tiled=True),
        "global_scatter", (t,), {})


def global_gather(x, local_count, global_count, group=None):
    """Inverse of global_scatter (global_gather_op.cc): return expert outputs
    to the token-owner ranks."""
    from .....core.op import apply_op
    from .....distributed import collective as coll

    g = coll._group(group)
    if not coll._in_trace(g):
        return x if isinstance(x, Tensor) else Tensor(_unwrap(x),
                                                      _internal=True)
    axis = g.axis_name
    t = x if isinstance(x, Tensor) else Tensor(_unwrap(x), _internal=True)
    return apply_op(
        lambda v: jax.lax.all_to_all(v, axis, split_axis=1, concat_axis=0,
                                     tiled=True),
        "global_gather", (t,), {})
