"""MoELayer — parity with incubate/distributed/models/moe/moe_layer.py:244.

The reference dispatches tokens with variable-length CUDA alltoalls
(global_scatter/global_gather ops) driven by per-expert counts computed on
device.  TPU-native formulation: GShard-style fixed-capacity dispatch/combine
einsums (static shapes, MXU-friendly, XLA fuses the one-hots into the
matmuls); expert parallelism is a `lax.all_to_all` over the expert mesh axis
when the layer runs under shard_map (utils.global_scatter/global_gather), and
a plain unrolled expert loop otherwise.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .....core.op import apply_op
from .....core.tensor import Tensor
from .....nn.layer_base import Layer
from .....nn.layer.container import LayerList
from .....ops.manipulation import stack
from .....distributed import collective as coll
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate
from .utils import global_gather, global_scatter


def _build_gate(gate, d_model, num_expert, world_size):
    if isinstance(gate, BaseGate):
        return gate
    if gate is None:
        gate = {"type": "gshard"}
    if isinstance(gate, str):
        gate = {"type": gate}
    cfg = dict(gate)
    kind = cfg.pop("type", "gshard")
    top_k = cfg.pop("top_k", 2 if kind != "switch" else 1)
    if kind == "naive":
        return NaiveGate(d_model, num_expert, world_size, topk=top_k)
    if kind == "gshard":
        return GShardGate(d_model, num_expert, world_size, topk=top_k, **cfg)
    if kind == "switch":
        return SwitchGate(d_model, num_expert, world_size, topk=top_k, **cfg)
    raise ValueError(f"unknown gate type {kind!r}")


class MoELayer(Layer):
    """Mixture of experts with optional expert parallelism.

    Args mirror moe_layer.py:244: `experts` is the list of THIS rank's
    experts; `moe_group` carries the expert-parallel axis; `gate` is a config
    dict ({"type": "gshard"/"switch"/"naive", "top_k": k}) or a BaseGate.
    `capacity_factor` scales the per-expert token capacity (GShard uses
    `2*N/E` for top-2; reference applies (1.2, 2.4) train/eval caps inside
    the gates).
    """

    def __init__(self, d_model, experts, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, recompute_ctx=None,
                 capacity_factor=1.2):
        super().__init__()
        self.d_model = d_model
        if not isinstance(experts, LayerList):
            experts = LayerList(list(experts))
        self.experts = experts
        self.num_expert = len(experts)
        self.moe_group = moe_group
        self.world_size = getattr(moe_group, "nranks", 1) if moe_group else 1
        self.capacity_factor = capacity_factor
        self.recompute_interval = recompute_interval
        self.gate = _build_gate(gate, d_model, self.num_expert,
                                self.world_size)
        self.top_k = self.gate.top_k

    # -- helpers -------------------------------------------------------------
    def _capacity(self, n_tokens: int) -> int:
        """Per-expert token capacity.  Gates carrying a (train, eval)
        capacity pair (GShard/Switch, gshard_gate.py capacity=(1.2, 2.4))
        override the layer's capacity_factor by mode."""
        e = self.gate.tot_expert
        factor = self.capacity_factor
        gate_cap = getattr(self.gate, "capacity", None)
        if gate_cap is not None:
            factor = gate_cap[0] if self.training else gate_cap[1]
        cap = int(math.ceil(factor * self.top_k * n_tokens / e))
        return max(cap, 4)

    def _dispatch_combine(self, val, idx, n_tokens, capacity):
        """Build the GShard combine tensor [N, E, C]: each token's normalized
        gate weight placed at its (expert, position) slot.  Differentiable in
        the gate values; runs as one framework op so the eager tape sees it."""
        e, k = self.gate.tot_expert, self.top_k

        def build(valv, idxv):
            valid = idxv >= 0
            # gate values are router probabilities; k=1 keeps p_top1 as the
            # scale (Switch), k>1 renormalizes among the selected (GShard)
            w = jnp.where(valid, valv, 0.0)
            if k > 1:
                denom = jnp.maximum(w.sum(axis=-1, keepdims=True), 1e-9)
                w = w / denom
            oh = jax.nn.one_hot(jnp.clip(idxv, 0, e - 1), e,
                                dtype=jnp.int32) * valid[..., None]  # [N,k,E]
            # priority: k=0 choices fill capacity before k=1 (GShard)
            oh_flat = oh.transpose(1, 0, 2).reshape(k * n_tokens, e)
            pos = jnp.cumsum(oh_flat, axis=0) - 1  # [kN,E] slot per expert
            pos = (pos * oh_flat).sum(axis=-1)  # [kN]
            keep = (pos < capacity) & (oh_flat.sum(axis=-1) > 0)
            pos_oh = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                                    dtype=valv.dtype)  # [kN,C]
            combine = jnp.einsum("se,sc,s->sec", oh_flat.astype(valv.dtype),
                                 pos_oh, keep.astype(valv.dtype))
            combine = combine.reshape(k, n_tokens, e, capacity)
            return jnp.einsum("knec,kn->nec", combine, w.transpose(1, 0))

        return apply_op(build, "moe_dispatch_combine", (val, idx), {})

    def _run_experts(self, dispatched: Tensor) -> Tensor:
        """dispatched: [E_total, C, d] -> [E_total, C, d] through the experts,
        exchanging over the expert axis when bound."""
        in_trace = self.moe_group is not None and coll._in_trace(self.moe_group)
        if in_trace and self.world_size > 1:
            x = global_scatter(dispatched, None, None, group=self.moe_group)
            outs = [self.experts[i](x[i]) for i in range(self.num_expert)]
            return global_gather(stack(outs, axis=0), None, None,
                                 group=self.moe_group)
        if dispatched.shape[0] != self.num_expert:
            raise ValueError(
                f"{dispatched.shape[0]} global experts but {self.num_expert} "
                "local experts and no bound expert-parallel axis; run under "
                "shard_map over the moe_group axis or provide all experts")
        outs = [self.experts[i](dispatched[i])
                for i in range(self.num_expert)]
        return stack(outs, axis=0)

    # -- forward -------------------------------------------------------------
    def forward(self, inp):
        x = inp if isinstance(inp, Tensor) else Tensor(jnp.asarray(inp),
                                                       _internal=True)
        orig_shape = tuple(x.shape)
        d = orig_shape[-1]
        tokens = x.reshape([-1, d])
        n = tokens.shape[0]
        cap = self._capacity(n)

        val, idx = self.gate(tokens)
        combine = self._dispatch_combine(val, idx, n, cap)

        def disp(cmb, tok):
            return jnp.einsum("nec,nd->ecd", (cmb > 0).astype(tok.dtype), tok)

        dispatched = apply_op(disp, "moe_dispatch", (combine, tokens), {})
        expert_out = self._run_experts(dispatched)

        def comb(cmb, eo):
            return jnp.einsum("nec,ecd->nd", cmb.astype(eo.dtype), eo)

        out = apply_op(comb, "moe_combine", (combine, expert_out), {})
        return out.reshape(orig_shape)
