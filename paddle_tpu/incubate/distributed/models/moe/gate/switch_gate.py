"""SwitchGate — parity with incubate/.../moe/gate/switch_gate.py: top-1
(Switch Transformer) routing with the switch load-balancing loss."""
from __future__ import annotations

import jax

from .naive_gate import NaiveGate


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size,
                 topk=1, switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        if topk != 1:
            raise ValueError("topk should be 1 in SwitchGate")
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps
        self.capacity = capacity
        self.group = group

    def forward(self, inp):
        from ......core import random as random_mod
        from ......core.op import apply_op

        score = self.gate(inp)
        e = self.tot_expert
        if self.training:
            # reference adds multiplicative jitter noise while training
            key = random_mod.next_key()
            lo, hi = 1.0 - self.switch_eps, 1.0 + self.switch_eps

            def jitter(s):
                noise = jax.random.uniform(key, s.shape, dtype=s.dtype,
                                           minval=lo, maxval=hi)
                return s * noise

            score = apply_op(jitter, "switch_jitter", (score,), {})

        def route(s):
            probs = jax.nn.softmax(s, axis=-1)
            top1_val = probs.max(axis=-1, keepdims=True)
            top1_idx = probs.argmax(axis=-1, keepdims=True)
            # switch balance loss: E * sum_e(token_fraction_e * mean_prob_e)
            ce = jax.nn.one_hot(top1_idx[..., 0], e,
                                dtype=probs.dtype).mean(axis=0)
            me = probs.mean(axis=0)
            return top1_val, top1_idx, (me * ce).sum() * float(e)

        top1_val, top1_idx, loss = apply_op(route, "switch_route", (score,), {})
        top1_idx.stop_gradient = True
        self.set_loss(loss)
        return top1_val, top1_idx
