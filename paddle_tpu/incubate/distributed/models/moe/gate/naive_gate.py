"""NaiveGate — parity with incubate/.../moe/gate/naive_gate.py: a linear
scorer with top-k selection and no balancing loss."""
from __future__ import annotations

import jax.lax as lax

from ......core.op import apply_op
from ......nn import Linear
from .base_gate import BaseGate


class NaiveGate(BaseGate):
    def __init__(self, d_model, num_expert, world_size, topk=2):
        super().__init__(num_expert, world_size)
        self.gate = Linear(d_model, self.tot_expert)
        self.top_k = topk

    def score(self, inp):
        return self.gate(inp)

    def forward(self, inp, return_all_scores=False):
        gate = self.gate(inp)
        k = self.top_k

        # top-k over the full-softmax probabilities, so the returned values
        # are router probabilities (Switch top-1 scales expert outputs by
        # p_top1; for k>1 the combine renormalizes among the selected, which
        # equals GShard's softmax-then-renormalize)
        def probs_topk(g):
            import jax
            return lax.top_k(jax.nn.softmax(g, axis=-1), k)

        gate_top_k_val, gate_top_k_idx = apply_op(
            probs_topk, "top_k", (gate,), {})
        gate_top_k_idx.stop_gradient = True
        if return_all_scores:
            return gate_top_k_val, gate_top_k_idx, gate
        return gate_top_k_val, gate_top_k_idx
