"""GShardGate — parity with incubate/.../moe/gate/gshard_gate.py: top-2
gating with capacity limiting, random second-expert routing and the GShard
load-balancing auxiliary loss (mean gate fraction x mean dispatch fraction
per expert, scaled by E)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ......core import random as random_mod
from ......core.op import apply_op
from ......core.tensor import Tensor
from .naive_gate import NaiveGate


class GShardGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size,
                 topk=2, capacity=(1.2, 2.4), random_routing=True,
                 group=None):
        if topk != 2:
            raise ValueError("topk should be 2 in GShardGate")
        super().__init__(d_model, num_expert, world_size, topk=2)
        self.capacity = capacity
        self.random_routing = random_routing
        self.group = group

    def forward(self, x):
        topk_val, topk_idx, gate_score = super().forward(
            x, return_all_scores=True)
        n_tokens, e = gate_score.shape[0], self.tot_expert

        # GShard aux loss (gshard_gate.py: me*ce balance loss), kept on the
        # tape so it can join the training loss
        def aux(s, idx):
            probs = jax.nn.softmax(s, axis=-1)
            me = probs.mean(axis=0)
            ce = jax.nn.one_hot(idx[..., 0], e, dtype=probs.dtype).mean(axis=0)
            return (me * ce).sum() * float(e)

        self.set_loss(apply_op(aux, "gshard_balance_loss",
                               (gate_score, topk_idx), {}))

        if self.random_routing and self.training:
            # keep the 2nd expert with prob 2*p2 (random_routing_op); topk_val
            # already holds router probabilities.  Training-only: eval keeps
            # deterministic top-2 so serving is reproducible.
            key = random_mod.next_key()
            prob = jax.random.uniform(key, (n_tokens,),
                                      dtype=gate_score._value.dtype)
            second = jnp.where(prob < 2.0 * topk_val._value[..., 1],
                               topk_idx._value[..., 1], -1)
            topk_idx = Tensor(
                jnp.stack([topk_idx._value[..., 0], second], axis=-1),
                stop_gradient=True, _internal=True)
        return topk_val, topk_idx
