"""ClipGradForMOEByGlobalNorm — parity with incubate/.../moe/grad_clip.py.

The reference computes the global norm in two parts: non-expert params
(allreduced norm across the moe group, since they are replicated) and expert
params (each rank's experts are distinct, so their norm contributions are
summed WITHOUT dividing by the group size).  Under the single-controller jax
runtime every value is already the global view, so both parts reduce to one
sum; the class keeps the reference's surface (is_expert_param_func,
moe_group) for source compatibility.
"""
from __future__ import annotations

import jax.numpy as jnp

from .....core.autograd import no_grad
from .....core.tensor import Tensor
from .....nn.clip import ClipGradByGlobalNorm


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    def __init__(self, clip_norm, is_expert_param_func=None, moe_group=None,
                 group_name="default_moe_group"):
        super().__init__(clip_norm, group_name=group_name)
        self.is_expert_param_func = is_expert_param_func
        self.moe_group = moe_group

    @no_grad()
    def _clip(self, params_grads):
        normal, expert = [], []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            if self.is_expert_param_func is not None and \
                    self.is_expert_param_func(p):
                expert.append(g)
            else:
                normal.append(g)
        sum_sq = 0.0
        for g in normal + expert:
            v = g._value if isinstance(g, Tensor) else g
            sum_sq = sum_sq + jnp.sum(jnp.square(v.astype(jnp.float32)))
        global_norm = jnp.sqrt(sum_sq)
        scale = jnp.minimum(1.0, self.clip_norm /
                            jnp.maximum(global_norm, 1e-12))
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            v = g._value if isinstance(g, Tensor) else g
            out.append((p, Tensor((v * scale).astype(v.dtype),
                                  _internal=True)))
        return out
