"""paddle.incubate.passes — IR-pass namespace (reference:
incubate/passes/fuse_resnet_unit_pass.py rewrites conv+BN(+add)+relu
subgraphs into the fused resnet_unit op).

TPU-native: XLA's fusion pipeline performs this rewrite during
compilation (docs/PERF.md measured its conv+BN chains at roofline), so
`fuse_resnet_unit()` records the request and returns — the semantics the
pass would produce are what the compiler already emits.  The
`ResNetUnit` layer itself lives in paddle.incubate.operators."""
from __future__ import annotations

_requested = False


def fuse_resnet_unit():
    """API-parity entry: on TPU the fusion is the compiler's job; this
    marks the intent (inspectable via `fuse_resnet_unit_requested()`)."""
    global _requested
    _requested = True


def fuse_resnet_unit_requested() -> bool:
    return _requested
