"""paddle.incubate.autotune — parity with
python/paddle/incubate/autotune.py (set_config:23: three tuning domains
"kernel" / "layout" / "dataloader", accepting a dict or a JSON file).

TPU mapping of each domain:
- kernel: XLA autotunes its own kernels during compilation; the knob
  gates our opt-in Pallas alternates instead (flash attention is always
  on; the measured-off-by-default LN kernels stay off unless the user
  flips them explicitly — see docs/PERF.md dead-end list).
- layout: toggles nn.channels_last (NHWC), the reference's AMP layout
  autotune analog.  Measured neutral on TPU (XLA re-lays out convs) but
  kept for API parity.
- dataloader: records the requested tuning for inspection via
  get_config() (the reference's reader.set_autotune_config analog; the
  DataLoader's worker heuristics are already dynamic here).
"""
from __future__ import annotations

import json
import warnings

__all__ = ["set_config"]

_config = {"kernel": {"enable": False, "tuning_range": [1, 10]},
           "layout": {"enable": False},
           "dataloader": {"enable": False}}


def get_config() -> dict:
    return dict(_config)


def set_config(config=None):
    """Enable/disable the autotune domains.  config: None (enable all),
    a dict like {"kernel": {"enable": True, "tuning_range": [1, 3]}},
    or a path to a JSON file with the same shape."""
    if config is None:
        for dom in _config.values():
            dom["enable"] = True
        _apply()
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise ValueError(
            "config should be a dict, a json file path, or None")
    for key in ("kernel", "layout", "dataloader"):
        if key not in config:
            continue
        dom = config[key]
        if "enable" in dom:
            if not isinstance(dom["enable"], bool):
                warnings.warn(f"{key}.enable should be bool")
            else:
                _config[key]["enable"] = dom["enable"]
        if key == "kernel" and "tuning_range" in dom:
            if isinstance(dom["tuning_range"], (list, tuple)):
                _config[key]["tuning_range"] = list(dom["tuning_range"])
            else:
                warnings.warn("kernel.tuning_range should be a list")
    _apply()


def _apply():
    from ..nn import layout as _layout
    _layout.set_global_channels_last(_config["layout"]["enable"])
