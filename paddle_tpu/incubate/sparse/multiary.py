"""incubate/sparse/multiary.py parity."""
from ...sparse import addmm  # noqa: F401
