"""incubate/sparse/unary.py parity (value-wise ops)."""
from ...sparse import (abs, asin, asinh, atan, atanh, cast,  # noqa: F401
                       divide_scalar, expm1, leaky_relu, log1p, pow,
                       relu, relu6, scale, sin, sinh, softmax, sqrt, square,
                       tan, tanh, transpose)
