"""paddle.incubate.sparse.nn — sparse layers (reference:
incubate/sparse/nn/__init__.py: ReLU, ReLU6, LeakyReLU, Softmax over the
sparse functional ops; Conv3D/SubmConv3D/MaxPool3D over the round-4
host-rulebook + device-segment-op kernels in paddle_tpu.sparse)."""
from __future__ import annotations

from ... import sparse as _sp
from ...nn.layer_base import Layer
from ...sparse import Conv3D, MaxPool3D, SubmConv3D  # noqa: F401

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax",
           "Conv3D", "SubmConv3D", "MaxPool3D"]


class ReLU(Layer):
    def forward(self, x):
        return _sp.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return _sp.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return _sp.leaky_relu(x, self._slope)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return _sp.softmax(x, self._axis)
