"""paddle.incubate.sparse.nn — sparse layers (reference:
incubate/sparse/nn/__init__.py: ReLU, ReLU6, LeakyReLU, Softmax over the
sparse functional ops; the 3-D sparse convs (Conv3D/SubmConv3D/MaxPool3D)
are backed by cuSPARSE gather-scatter kernels in the reference and are
not ported — jax.experimental.sparse has no submanifold conv; an import
error here would be dishonest, absence is)."""
from __future__ import annotations

from ... import sparse as _sp
from ...nn.layer_base import Layer

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax"]


class ReLU(Layer):
    def forward(self, x):
        return _sp.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return _sp.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return _sp.leaky_relu(x, self._slope)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return _sp.softmax(x, self._axis)
