"""paddle.incubate.sparse — the incubating sparse namespace (reference:
python/paddle/incubate/sparse/__init__.py re-exports creation/unary/
binary/multiary/nn).  This paddle version keeps sparse under incubate;
our implementations live in paddle_tpu.sparse — re-exported here with
the reference's submodule layout."""
from ...sparse import *  # noqa: F401,F403
from ...sparse import (SparseCooTensor, SparseCsrTensor,  # noqa: F401
                       sparse_coo_tensor, sparse_csr_tensor)
from . import binary, creation, multiary, nn, unary  # noqa: F401
