"""incubate/sparse/binary.py parity."""
from ...sparse import (add, divide, masked_matmul, matmul,  # noqa: F401
                       multiply, mv, subtract)
