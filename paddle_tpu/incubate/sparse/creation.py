"""incubate/sparse/creation.py parity."""
from ...sparse import sparse_coo_tensor, sparse_csr_tensor  # noqa: F401
