"""Fused transformer layers — parity with
incubate/nn/layer/fused_transformer.py (FusedBiasDropoutResidualLayerNorm:79,
FusedMultiHeadAttention:176, FusedFeedForward:437,
FusedTransformerEncoderLayer:641, FusedMultiTransformer:914).

Semantics follow the reference's CUDA-fused ops; the "fusion" is delegated to
XLA + the Pallas flash-attention kernel (see incubate.nn.__init__).
"""
from __future__ import annotations

from ....core.op import apply_op
from ....nn import functional as F
from ....nn.functional.attention import scaled_dot_product_attention
from ....nn.layer.common import Dropout, Linear
from ....nn.layer.container import LayerList
from ....nn.layer.norm import LayerNorm
from ....nn.layer_base import Layer


class FusedBiasDropoutResidualLayerNorm(Layer):
    """out = layer_norm(residual + dropout(x + bias)) — fused_transformer.py:79
    (fused_bias_dropout_residual_layer_norm op)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.linear_bias = self.create_parameter(
            shape=[embed_dim], attr=bias_attr, dtype=self._dtype, is_bias=True)
        self.dropout = Dropout(dropout_rate)
        self.norm = LayerNorm(embed_dim, epsilon=epsilon,
                              weight_attr=weight_attr)

    def forward(self, x, residual):
        y = x + self.linear_bias
        y = self.dropout(y)
        return self.norm(residual + y)


class FusedMultiHeadAttention(Layer):
    """Pre/post-LN multi-head self-attention with fused residual path —
    fused_transformer.py:176 (fused_attention_op.cu semantics: qkv in one
    GEMM, flash-attention core, out-proj, bias+dropout+residual+LN)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv_proj = Linear(embed_dim, 3 * embed_dim,
                               weight_attr=qkv_weight_attr,
                               bias_attr=qkv_bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim,
                               weight_attr=linear_weight_attr,
                               bias_attr=linear_bias_attr)
        self.pre_ln = LayerNorm(embed_dim, epsilon=epsilon,
                                weight_attr=pre_ln_scale_attr,
                                bias_attr=pre_ln_bias_attr)
        self.ln = LayerNorm(embed_dim, epsilon=epsilon,
                            weight_attr=ln_scale_attr, bias_attr=ln_bias_attr)
        self.attn_dropout_rate = attn_dropout_rate
        self.dropout = Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        if key is not None or value is not None:
            raise NotImplementedError(
                "FusedMultiHeadAttention is self-attention only (fused qkv "
                "GEMM); pass query alone, or use nn.MultiHeadAttention for "
                "cross-attention")
        if cache is not None:
            raise NotImplementedError(
                "incremental decode cache is not supported by "
                "FusedMultiHeadAttention yet; use nn.MultiHeadAttention")
        residual = query
        x = self.pre_ln(query) if self.normalize_before else query
        qkv = self.qkv_proj(x)
        b, t = qkv.shape[0], qkv.shape[1]
        nh, hd = self.num_heads, self.head_dim

        def split_qkv(qv):
            r = qv.reshape(b, t, 3, nh, hd)
            return r[:, :, 0], r[:, :, 1], r[:, :, 2]

        q, k, v = apply_op(split_qkv, "qkv_split", (qkv,), {})
        out = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_rate,
            training=self.training)
        out = out.reshape([b, t, self.embed_dim])
        out = self.out_proj(out)
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(Layer):
    """linear→act→dropout→linear→bias+dropout+residual+LN —
    fused_transformer.py:437 (fused_feedforward_op.cu semantics)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward,
                              weight_attr=linear1_weight_attr,
                              bias_attr=linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model,
                              weight_attr=linear2_weight_attr,
                              bias_attr=linear2_bias_attr)
        self.pre_ln = LayerNorm(d_model, epsilon=epsilon,
                                weight_attr=ln1_scale_attr,
                                bias_attr=ln1_bias_attr)
        self.ln = LayerNorm(d_model, epsilon=epsilon,
                            weight_attr=ln2_scale_attr, bias_attr=ln2_bias_attr)
        self.activation = activation
        self.act_dropout = Dropout(dropout_rate if act_dropout_rate is None
                                   else act_dropout_rate)
        self.dropout = Dropout(dropout_rate)

    def forward(self, src):
        residual = src
        x = self.pre_ln(src) if self.normalize_before else src
        x = self.linear1(x)
        x = getattr(F, self.activation)(x)
        x = self.act_dropout(x)
        x = self.linear2(x)
        out = residual + self.dropout(x)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    """fused attention + fused FFN — fused_transformer.py:641."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        return self.ffn(out)


class FusedMoELayer(Layer):
    """incubate/nn FusedMoELayer parity: an MoE FFN block with the fused-op
    signature (d_model, dim_feedforward, num_expert, top_k); expert compute
    and the capacity dispatch ride the incubate MoELayer (all_to_all over
    the expert axis when bound)."""

    def __init__(self, d_model, dim_feedforward, num_expert, top_k=2,
                 approximate=True, moe_group=None, mp_group=None,
                 ln_scale=None, ln_bias=None, gate_weight=None,
                 gate_bias=None, linear1_weights=None, linear1_biases=None,
                 linear2_weights=None, linear2_biases=None):
        super().__init__()
        from ....incubate.distributed.models.moe import MoELayer
        from ....nn.layer.activation import GELU
        from ....nn.layer.container import Sequential

        injected = [ln_scale, ln_bias, gate_weight, gate_bias,
                    linear1_weights, linear1_biases, linear2_weights,
                    linear2_biases]
        if any(v is not None for v in injected) or mp_group is not None:
            raise NotImplementedError(
                "FusedMoELayer weight injection / mp_group are not "
                "supported; build the layer then set_state_dict the "
                "converted weights")

        def expert():
            return Sequential(Linear(d_model, dim_feedforward),
                              GELU(approximate=approximate),
                              Linear(dim_feedforward, d_model))

        self.norm = LayerNorm(d_model)
        if top_k == 2:
            gate = {"type": "gshard"}
        elif top_k == 1:
            gate = {"type": "switch"}  # Switch routing keeps balance loss
        else:
            gate = {"type": "naive", "top_k": top_k}
        self.moe = MoELayer(d_model,
                            [expert() for _ in range(num_expert)],
                            gate=gate, moe_group=moe_group)

    def forward(self, x):
        return x + self.moe(self.norm(x))


class FusedMultiTransformer(Layer):
    """N stacked pre-LN transformer blocks — fused_transformer.py:914
    (fused_multi_transformer_op.cu: the whole decoder stack as one fused op;
    here one jit region the compiler schedules)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, ln_scale_attrs=None,
                 ln_bias_attrs=None, num_layers=-1, nranks=1, ring_id=-1,
                 name=None, **kwargs):
        super().__init__()
        if num_layers < 0:
            num_layers = 1
        self.layers = LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=normalize_before)
            for _ in range(num_layers)])

    def forward(self, src, attn_mask=None, caches=None, **kwargs):
        x = src
        for layer in self.layers:
            x = layer(x, src_mask=attn_mask)
        return x
