"""paddle.incubate.nn.functional parity: functional forms of the fused ops
(incubate/nn/functional/fused_transformer.py: fused_multi_head_attention
:371, fused_multi_transformer:661; fused_matmul_bias.py:21,80).  Each is
the reference kernel's pseudo-code composed from taped Tensor ops — XLA
fuses the epilogues so gradients flow to every input, and the attention
core rides the flash kernel via scaled_dot_product_attention."""
from __future__ import annotations

from ....core.tensor import Tensor
from ....nn import functional as _F
from ....nn.functional.attention import scaled_dot_product_attention


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(x, _internal=True)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """fused_matmul_bias.py:21 (cublasLt epilogue fusion; XLA fuses the
    bias add into the matmul's consumer chain here)."""
    from .... import ops as _ops
    out = _ops.matmul(x, y, transpose_x=transpose_x,
                      transpose_y=transpose_y)
    return out if bias is None else out + bias


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """fused_matmul_bias.py:80."""
    return fused_matmul_bias(x, weight, bias,
                             transpose_y=transpose_weight)


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True,
                               mode="upscale_in_train", ring_id=-1,
                               add_residual=True, name=None):
    """fused_transformer.py:371 — self-attention with the reference's
    fused-op semantics: qkv_weight [3, nh, hd, e], qkv_bias [3, nh, hd];
    returns out (and the updated cache_kv when one is passed).

    Composed entirely from taped Tensor ops so gradients flow to x and
    every weight (the reference op is differentiable; round-3 advice
    found the jnp-composed version severed the tape)."""
    from .... import ops as _ops
    x = _as_tensor(x)
    qkv_weight = _as_tensor(qkv_weight)
    residual = x
    h = x
    if pre_layer_norm:
        h = _F.layer_norm(x, x.shape[-1:], weight=pre_ln_scale,
                          bias=pre_ln_bias, epsilon=pre_ln_epsilon)
    three, nh, hd, e = tuple(qkv_weight.shape)
    qkv = _ops.einsum("bse,thde->bsthd", h, qkv_weight)
    if qkv_bias is not None:
        qkv = qkv + _as_tensor(qkv_bias)   # [3,nh,hd] broadcasts over [b,s,·]
    q, k, v = (qkv[:, :, i] for i in range(3))          # [b, s, nh, hd]
    if cache_kv is not None:
        cache_kv = _as_tensor(cache_kv)                  # [2, b, nh, t, hd]
        k = _ops.concat([_ops.transpose(cache_kv[0], [0, 2, 1, 3]), k],
                        axis=1)
        v = _ops.concat([_ops.transpose(cache_kv[1], [0, 2, 1, 3]), v],
                        axis=1)
    del e  # embed dim only documents the qkv_weight layout
    out = scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
        training=training)                               # [b, s, nh, hd]
    out = out.reshape([out.shape[0], out.shape[1], nh * hd])
    out = _F.linear(out, linear_weight, linear_bias)
    out = _F.dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = _F.layer_norm(out, out.shape[-1:], weight=ln_scale,
                            bias=ln_bias, epsilon=ln_epsilon)
    if cache_kv is not None:
        new_cache = _ops.stack([_ops.transpose(k, [0, 2, 1, 3]),
                                _ops.transpose(v, [0, 2, 1, 3])])
        return out, new_cache
    return out


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights,
                            qkv_biases, linear_weights, linear_biases,
                            ffn_ln_scales, ffn_ln_biases, ffn1_weights,
                            ffn1_biases, ffn2_weights, ffn2_biases,
                            pre_layer_norm=True, epsilon=1e-5,
                            cache_kvs=None, time_step=None, attn_mask=None,
                            dropout_rate=0.0, activation="gelu",
                            training=False, mode="upscale_in_train",
                            trans_qkvw=True, ring_id=-1, name=None):
    """fused_transformer.py:661 — N pre-LN transformer layers in one
    call (per-layer weight LISTS, optional KV caches for generation).
    qkv_weights[i]: [3, nh, hd, e] when trans_qkvw (the reference
    default)."""
    from .... import ops as _ops
    out = x
    new_caches = [] if cache_kvs is not None else None
    n = len(qkv_weights)
    for i in range(n):
        qw = _as_tensor(qkv_weights[i])
        if not trans_qkvw:                 # [e, 3, nh, hd] -> [3, nh, hd, e]
            qw = _ops.transpose(qw, [1, 2, 3, 0])
        cache_i = None
        if cache_kvs is not None:
            cache_i = _as_tensor(cache_kvs[i])
            if time_step is not None:
                # reference decode contract: a FIXED-size cache
                # [2, b, nh, max_len, hd] whose valid prefix is
                # time_step — attending over the unwritten tail would
                # softmax against garbage keys
                t = int(time_step)
                cache_i = cache_i[:, :, :, :t]
        ln_s = ln_scales[i] if ln_scales else None
        ln_b = ln_biases[i] if ln_biases else None
        attn = fused_multi_head_attention(
            out, qw, linear_weights[i],
            pre_layer_norm=pre_layer_norm,
            # pre-LN consumes ln as the PRE norm; post-LN as the POST one
            pre_ln_scale=ln_s if pre_layer_norm else None,
            pre_ln_bias=ln_b if pre_layer_norm else None,
            ln_scale=None if pre_layer_norm else ln_s,
            ln_bias=None if pre_layer_norm else ln_b,
            pre_ln_epsilon=epsilon, ln_epsilon=epsilon,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            cache_kv=cache_i,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, training=training, mode=mode)
        if cache_kvs is not None:
            attn, cache = attn
            new_caches.append(cache)
        fln_s = ffn_ln_scales[i] if ffn_ln_scales else None
        fln_b = ffn_ln_biases[i] if ffn_ln_biases else None
        out = fused_feedforward(
            attn, ffn1_weights[i],
            ffn1_biases[i] if ffn1_biases else None,
            ffn2_weights[i],
            ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=fln_s, ln1_bias=fln_b,
            ln2_scale=fln_s, ln2_bias=fln_b,
            ln1_epsilon=epsilon, ln2_epsilon=epsilon,
            dropout1_rate=dropout_rate,
            dropout2_rate=dropout_rate, activation=activation,
            pre_layer_norm=pre_layer_norm, training=training, mode=mode)
    if cache_kvs is not None:
        return out, new_caches
    return out


def fused_feedforward(x, linear1_weight, linear1_bias, linear2_weight,
                      linear2_bias, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode='upscale_in_train',
                      ring_id=-1, name=None):
    residual = x
    if pre_layer_norm:
        x = _F.layer_norm(x, x.shape[-1:], weight=ln1_scale, bias=ln1_bias,
                          epsilon=ln1_epsilon)
    y = _F.linear(x, linear1_weight, linear1_bias)
    y = getattr(_F, activation)(y)
    y = _F.dropout(y, p=dropout1_rate, training=training)
    y = _F.linear(y, linear2_weight, linear2_bias)
    y = _F.dropout(y, p=dropout2_rate, training=training)
    out = residual + y
    if not pre_layer_norm:
        out = _F.layer_norm(out, out.shape[-1:], weight=ln2_scale,
                            bias=ln2_bias, epsilon=ln2_epsilon)
    return out


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True,
                                           mode='upscale_in_train', name=None):
    y = x if bias is None else x + bias
    y = _F.dropout(y, p=dropout_rate, training=training)
    out = residual + y
    return _F.layer_norm(out, out.shape[-1:], weight=ln_scale, bias=ln_bias,
                         epsilon=ln_epsilon)
