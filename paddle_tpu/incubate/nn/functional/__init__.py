"""paddle.incubate.nn.functional parity: functional forms of the fused ops
(incubate/nn/functional/fused_transformer.py)."""
from __future__ import annotations

from ....nn import functional as _F
from ....nn.functional.attention import scaled_dot_product_attention


def fused_feedforward(x, linear1_weight, linear1_bias, linear2_weight,
                      linear2_bias, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode='upscale_in_train',
                      ring_id=-1, name=None):
    residual = x
    if pre_layer_norm:
        x = _F.layer_norm(x, x.shape[-1:], weight=ln1_scale, bias=ln1_bias,
                          epsilon=ln1_epsilon)
    y = _F.linear(x, linear1_weight, linear1_bias)
    y = getattr(_F, activation)(y)
    y = _F.dropout(y, p=dropout1_rate, training=training)
    y = _F.linear(y, linear2_weight, linear2_bias)
    y = _F.dropout(y, p=dropout2_rate, training=training)
    out = residual + y
    if not pre_layer_norm:
        out = _F.layer_norm(out, out.shape[-1:], weight=ln2_scale,
                            bias=ln2_bias, epsilon=ln2_epsilon)
    return out


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True,
                                           mode='upscale_in_train', name=None):
    y = x if bias is None else x + bias
    y = _F.dropout(y, p=dropout_rate, training=training)
    out = residual + y
    return _F.layer_norm(out, out.shape[-1:], weight=ln_scale, bias=ln_bias,
                         epsilon=ln_epsilon)
