"""paddle.incubate.nn parity — the fused transformer family
(incubate/nn/layer/fused_transformer.py:79,176,437,641,914).

On GPU the reference backs these with monolithic CUDA kernels
(operators/fused/fused_attention_op.cu, fused_feedforward_op.cu,
fused_multi_transformer_op.cu).  On TPU the same fusion is the compiler's
job: these layers express the exact op sequence; XLA fuses the
bias/dropout/residual/layernorm chains and the attention core routes to the
Pallas flash kernel (paddle_tpu.kernels.flash_attention).
"""
from .layer.fused_transformer import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm,
    FusedFeedForward,
    FusedMoELayer,
    FusedMultiHeadAttention,
    FusedMultiTransformer,
    FusedTransformerEncoderLayer,
)
from . import functional  # noqa: F401
