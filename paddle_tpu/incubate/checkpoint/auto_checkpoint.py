"""Auto-checkpoint — parity with fluid/incubate/checkpoint/
auto_checkpoint.py (`TrainEpochRange`:267 wraps the epoch loop, snapshots
state per epoch keyed by job id, and transparently resumes after a
relaunch; the reference writes to HDFS via checkpoint_saver.py, here to the
sharded local/NFS checkpoint layout).
"""
from __future__ import annotations

import os

from ...framework.checkpoint import AsyncCheckpointSaver


def _job_id() -> str:
    return os.environ.get("PADDLE_JOB_ID",
                          os.environ.get("PADDLE_ELASTIC_JOB_ID", "default"))


def _root_dir() -> str:
    return os.environ.get("PADDLE_AUTO_CHECKPOINT_DIR",
                          os.path.join(".", "auto_checkpoint"))


class TrainEpochRange:
    """for epoch in TrainEpochRange(E, name): ...  — saves registered
    model/optimizer state at each epoch end and resumes from the last saved
    epoch after a restart (auto_checkpoint.py:267/:636)."""

    def __init__(self, max_epoch_num: int, name: str | None = None,
                 save_checkpoint_inter: int = 1, checkpoint_dir=None,
                 keep_last: int = 3, fs=None):
        self.max_epoch_num = max_epoch_num
        self.name = name or _job_id()
        self.save_inter = max(1, save_checkpoint_inter)
        base = checkpoint_dir or os.path.join(_root_dir(), self.name)
        # fs: a fleet.utils.fs client; HDFS/GCS checkpoints stage through
        # a local temp dir (reference auto_checkpoint.py:636 fs plumbing)
        self._saver = AsyncCheckpointSaver(base, keep_last=keep_last, fs=fs)
        self._registered = []  # (obj with state_dict/set_state_dict, tag)
        self._start_epoch = 0
        self._restored_state = None
        # restore_latest_valid: a corrupt/torn newest epoch falls back to
        # the previous committed one instead of failing the relaunch
        last, state = self._saver.restore_latest_valid()
        if last is not None:
            self._restored_state = state
            self._start_epoch = last + 1

    # -- registration (reference: exe/program snapshot; here state_dicts) ----
    def register(self, obj, tag: str | None = None):
        tag = tag or f"obj{len(self._registered)}"
        self._registered.append((obj, tag))
        if self._restored_state is not None and tag in self._restored_state:
            obj.set_state_dict(self._restored_state[tag])
        return self

    @property
    def start_epoch(self) -> int:
        return self._start_epoch

    def __iter__(self):
        for epoch in range(self._start_epoch, self.max_epoch_num):
            yield epoch
            if (epoch + 1) % self.save_inter == 0 or \
                    epoch == self.max_epoch_num - 1:
                self._snapshot(epoch)
        self._saver.wait()

    def _snapshot(self, epoch: int):
        state = {tag: obj.state_dict() for obj, tag in self._registered}
        self._saver.save(state, step=epoch)

    def save_checkpoint(self, epoch: int | None = None):
        self._snapshot(epoch if epoch is not None else self._start_epoch)
        self._saver.wait()


def train_epoch_range(max_epoch_num, save_checkpoint_inter=1):
    """auto_checkpoint.train_epoch_range generator parity."""
    r = TrainEpochRange(max_epoch_num,
                        save_checkpoint_inter=save_checkpoint_inter)
    yield from r
