"""paddle.incubate parity namespace (SURVEY §2.3 incubate: MoE expert
parallelism, fused nn layers, distributed models)."""
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import autotune  # noqa: F401
from . import checkpoint  # noqa: F401
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import operators  # noqa: F401
from . import passes  # noqa: F401
from . import optimizer  # noqa: F401
from . import multiprocessing  # noqa: F401
from . import sparse  # noqa: F401
from . import tensor  # noqa: F401
from .tensor import (segment_max, segment_mean, segment_min,  # noqa: F401
                     segment_sum)
