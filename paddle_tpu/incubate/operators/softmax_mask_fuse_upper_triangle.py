"""softmax_mask_fuse_upper_triangle — parity with
incubate/operators/softmax_mask_fuse_upper_triangle.py:23 (causal-masked
softmax without materializing the mask).  The lax.lt iota comparison is
fused by XLA into the softmax pass, matching the reference kernel's
intent on TPU."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op import defop

__all__ = ["softmax_mask_fuse_upper_triangle"]


@defop
def softmax_mask_fuse_upper_triangle(x):
    """x: [B, H, T, T] scores; masks the strict upper triangle (future
    positions) before the softmax."""
    t = x.shape[-1]
    rows = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    neg = jnp.asarray(jnp.finfo(
        x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.float32).min, x.dtype)
    masked = jnp.where(cols <= rows, x, neg)
    return jax.nn.softmax(masked, axis=-1)
