"""softmax_mask_fuse — parity with
incubate/operators/softmax_mask_fuse.py:23 (fused_softmax_mask CUDA
kernel: softmax(x + mask) in one pass).  On TPU the add feeds XLA's
softmax fusion directly — same single-pass execution, no custom kernel
needed."""
from __future__ import annotations

import jax

from ...core.op import defop

__all__ = ["softmax_mask_fuse"]


@defop
def softmax_mask_fuse(x, mask, name=None):
    """x: [B, H, T, T] attention scores; mask: [B, 1, T, T] additive mask
    (-10000-style).  Returns softmax(x + mask, axis=-1)."""
    return jax.nn.softmax(x + mask, axis=-1)
