"""Graph incubate operators — legacy names over paddle.geometric
(reference: incubate/operators/graph_send_recv.py:30,
graph_sample_neighbors.py, graph_reindex.py, graph_khop_sampler.py:23 —
all later stabilized under paddle.geometric, which is where our kernels
live)."""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor

__all__ = ["graph_send_recv", "graph_sample_neighbors", "graph_reindex",
           "graph_khop_sampler"]


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    from ...geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    from ...geometric import sample_neighbors
    return sample_neighbors(
        row, colptr, input_nodes, sample_size=sample_size, eids=eids,
        return_eids=return_eids,
        perm_buffer=perm_buffer if flag_perm_buffer else None)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    from ...geometric import reindex_graph
    return reindex_graph(x, neighbors, count, value_buffer, index_buffer)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop sampling + one reindex over the union frontier
    (reference graph_khop_sampler.py:23: returns edge_src, edge_dst,
    sample_index, reindex_nodes[, edge_eids])."""
    from ...geometric import reindex_graph, sample_neighbors

    frontier = input_nodes
    all_neigh, all_cnt, all_eids = [], [], []
    dst_nodes = []   # per-hop source frontiers, concatenated for reindex
    for size in sample_sizes:
        if return_eids:
            neigh, cnt, eids = sample_neighbors(
                row, colptr, frontier, sample_size=size,
                eids=sorted_eids, return_eids=True)
            all_eids.append(np.asarray(eids.numpy()).reshape(-1))
        else:
            neigh, cnt = sample_neighbors(row, colptr, frontier,
                                          sample_size=size)
        all_neigh.append(np.asarray(neigh.numpy()).reshape(-1))
        all_cnt.append(np.asarray(cnt.numpy()).reshape(-1))
        dst_nodes.append(np.asarray(
            frontier.numpy() if hasattr(frontier, "numpy") else frontier
        ).reshape(-1))
        frontier = Tensor(np.unique(all_neigh[-1]))
    dst_cat = np.concatenate(dst_nodes)
    neigh_cat = np.concatenate(all_neigh)
    cnt_cat = np.concatenate(all_cnt).astype(np.int32)
    edge_src, edge_dst, sample_index = reindex_graph(
        Tensor(dst_cat), Tensor(neigh_cat), Tensor(cnt_cat))
    # reindex id of the ORIGINAL input nodes = their positions (x-first
    # ordering contract of reindex_graph)
    n_in = len(np.asarray(
        input_nodes.numpy() if hasattr(input_nodes, "numpy")
        else input_nodes).reshape(-1))
    reindex_nodes = Tensor(np.arange(n_in, dtype=dst_cat.dtype))
    if return_eids:
        return (edge_src, edge_dst, sample_index, reindex_nodes,
                Tensor(np.concatenate(all_eids)))
    return edge_src, edge_dst, sample_index, reindex_nodes
