"""paddle.incubate.operators — parity with
python/paddle/incubate/operators/ (graph_send_recv:30,
graph_sample_neighbors, graph_reindex, graph_khop_sampler:23,
softmax_mask_fuse:23, softmax_mask_fuse_upper_triangle:23,
resnet_unit.ResNetUnit:125).

The graph ops delegate to paddle.geometric (same kernels, older names);
the softmax-mask fusions are expressed functionally — XLA fuses the mask
add into the softmax the way the reference's hand-written CUDA kernel
does; ResNetUnit composes conv+BN(+add)+relu, which is exactly the op
set the fused cudnn path computes, left to XLA's fusion on TPU."""
from .graph_ops import (graph_khop_sampler, graph_reindex,  # noqa: F401
                        graph_sample_neighbors, graph_send_recv)
from .resnet_unit import ResNetUnit, resnet_unit  # noqa: F401
from .softmax_mask_fuse import softmax_mask_fuse  # noqa: F401
from .softmax_mask_fuse_upper_triangle import (  # noqa: F401
    softmax_mask_fuse_upper_triangle)

__all__ = ["graph_send_recv", "graph_sample_neighbors", "graph_reindex",
           "graph_khop_sampler", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle", "ResNetUnit", "resnet_unit"]
