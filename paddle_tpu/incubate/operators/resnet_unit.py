"""ResNetUnit — parity with incubate/operators/resnet_unit.py:125 (the
cudnn fused conv+BN(+add)+relu block used by performance ResNets).

TPU-native: the same math composed from conv2d + batch_norm + add +
relu; XLA's conv/elementwise fusion is the TPU counterpart of the cudnn
fused op (docs/PERF.md measured XLA's conv+BN chains at roofline in
isolation — a hand kernel buys nothing here)."""
from __future__ import annotations

import numpy as np

from ... import nn
from ...nn import functional as F

__all__ = ["ResNetUnit", "resnet_unit"]


def resnet_unit(x, filter_x, scale_x, bias_x, mean_x, var_x, z=None,
                filter_z=None, scale_z=None, bias_z=None, mean_z=None,
                var_z=None, stride=1, stride_z=1, padding=0, dilation=1,
                groups=1, momentum=0.9, eps=1e-5, data_format="NHWC",
                fuse_add=False, has_shortcut=False, use_global_stats=False,
                is_test=False, act="relu"):
    """Functional form: y = act(BN(conv(x)) [+ BN(conv(z)) | + z])."""
    def branch(inp, w, scale, bias, mean, var, s, pad):
        out = F.conv2d(inp, w, stride=s, padding=pad,
                       dilation=dilation, groups=groups,
                       data_format=data_format)
        return F.batch_norm(out, mean, var, scale, bias,
                            training=not is_test, momentum=momentum,
                            epsilon=eps, data_format=data_format,
                            use_global_stats=use_global_stats)

    out = branch(x, filter_x, scale_x, bias_x, mean_x, var_x, stride,
                 padding)
    if has_shortcut:
        # the shortcut conv is 1x1: no spatial padding (reference builds
        # its conv_z attrs with padding 0)
        out = out + branch(z, filter_z, scale_z, bias_z, mean_z, var_z,
                           stride_z, 0)
    elif fuse_add:
        out = out + z
    if act == "relu":
        out = F.relu(out)
    return out


class ResNetUnit(nn.Layer):
    """Layer form (reference ResNetUnit Layer): owns the conv filters and
    BN params for the main branch and (optionally) the shortcut."""

    def __init__(self, num_channels_x, num_filters, filter_size, stride=1,
                 momentum=0.9, eps=1e-5, data_format="NHWC", act="relu",
                 fuse_add=False, has_shortcut=False, use_global_stats=False,
                 is_test=False, filter_x_attr=None, scale_x_attr=None,
                 bias_x_attr=None, moving_mean_x_name=None,
                 moving_var_x_name=None, num_channels_z=1, stride_z=1,
                 filter_z_attr=None, scale_z_attr=None, bias_z_attr=None,
                 moving_mean_z_name=None, moving_var_z_name=None):
        super().__init__()
        self._stride = stride
        self._stride_z = stride_z
        self._padding = (filter_size - 1) // 2
        self._momentum = momentum
        self._eps = eps
        self._data_format = data_format
        self._act = act
        self._fuse_add = fuse_add
        self._has_shortcut = has_shortcut
        self._use_global_stats = use_global_stats
        self._is_test = is_test

        k = (filter_size, filter_size)
        self.filter_x = self.create_parameter(
            (num_filters, num_channels_x // 1) + k, attr=filter_x_attr)
        self.scale_x = self.create_parameter(
            (num_filters,), attr=scale_x_attr, is_bias=False,
            default_initializer=nn.initializer.Constant(1.0))
        self.bias_x = self.create_parameter(
            (num_filters,), attr=bias_x_attr, is_bias=True)
        from ...core.tensor import Tensor
        self.register_buffer("mean_x",
                             Tensor(np.zeros(num_filters, "float32")))
        self.register_buffer("var_x",
                             Tensor(np.ones(num_filters, "float32")))
        if has_shortcut:
            self.filter_z = self.create_parameter(
                (num_filters, num_channels_z) + (1, 1), attr=filter_z_attr)
            self.scale_z = self.create_parameter(
                (num_filters,), attr=scale_z_attr,
                default_initializer=nn.initializer.Constant(1.0))
            self.bias_z = self.create_parameter(
                (num_filters,), attr=bias_z_attr, is_bias=True)
            self.register_buffer(
                "mean_z", Tensor(np.zeros(num_filters, "float32")))
            self.register_buffer(
                "var_z", Tensor(np.ones(num_filters, "float32")))
        else:
            self.filter_z = self.scale_z = self.bias_z = None
            self.mean_z = self.var_z = None

    def forward(self, x, z=None):
        return resnet_unit(
            x, self.filter_x, self.scale_x, self.bias_x, self.mean_x,
            self.var_x, z, self.filter_z, self.scale_z, self.bias_z,
            self.mean_z, self.var_z, self._stride, self._stride_z,
            self._padding, 1, 1, self._momentum, self._eps,
            self._data_format, self._fuse_add, self._has_shortcut,
            self._use_global_stats, self._is_test, self._act)
