"""paddle.incubate.autograd parity — higher-order/functional AD
(incubate/autograd: primrules.py/primx.py prim system, primapi.py, and the
functional Jacobian/Hessian/jvp/vjp API).

The reference lowers ops to primitive pairs (orig2prim/prim2orig) to get
transposable linearizations; jax's jvp/vjp/jacobian transforms ARE that
machinery, so this module is a thin functional surface over them operating
on framework Tensors.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "forward_grad", "grad"]


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return jnp.asarray(x)


def _wrap(v):
    if isinstance(v, (list, tuple)):
        return type(v)(_wrap(u) for u in v)
    return Tensor(v, _internal=True)


def _pure(func):
    def fn(*raw):
        out = func(*[Tensor(r, _internal=True) for r in raw])
        return _unwrap(out)
    return fn


def jvp(func, xs, v=None):
    """Forward-mode: returns (outputs, jvp_result) (primapi.jvp parity)."""
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    raw = [_unwrap(x) for x in xs]
    if v is None:
        tangents = [jnp.ones_like(r) for r in raw]
    else:
        v = v if isinstance(v, (list, tuple)) else [v]
        tangents = [_unwrap(t) for t in v]
    out, tangent_out = jax.jvp(_pure(func), tuple(raw), tuple(tangents))
    return _wrap(out), _wrap(tangent_out)


def vjp(func, xs, v=None):
    """Reverse-mode: returns (outputs, vjp_result) (primapi.vjp parity)."""
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    raw = [_unwrap(x) for x in xs]
    out, vjp_fn = jax.vjp(_pure(func), *raw)
    if v is None:
        # cotangent must mirror the output's container type exactly
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        cot = _unwrap(v)
    grads = vjp_fn(cot)
    grads = grads[0] if len(grads) == 1 else list(grads)
    return _wrap(out), _wrap(grads)


class Jacobian:
    """autograd.Jacobian parity: lazy J[i, j] over a function of one or more
    inputs; materialized via jax.jacrev."""

    def __init__(self, func, xs, is_batched=False):
        self._func = func
        self._xs = xs if isinstance(xs, (list, tuple)) else [xs]
        self._is_batched = is_batched
        self._mat = None

    def _compute(self):
        if self._mat is None:
            raw = [_unwrap(x) for x in self._xs]
            jac = jax.jacrev(_pure(self._func),
                             argnums=tuple(range(len(raw))))(*raw)
            jac = jac[0] if len(raw) == 1 else jac
            if self._is_batched:
                # [B, out, B, in] diagonal → [B, out, in]
                def take_diag(j):
                    b = j.shape[0]
                    return jnp.stack([j[i].reshape(-1, *j.shape[2:])[..., :]
                                      [:, i] for i in range(b)])
                jac = jax.tree_util.tree_map(take_diag, jac)
            self._mat = jax.tree_util.tree_map(
                lambda j: Tensor(j, _internal=True), jac)
        return self._mat

    def __getitem__(self, idx):
        m = self._compute()
        if isinstance(m, Tensor):
            return m[idx]
        return [t[idx] for t in m] if isinstance(m, (list, tuple)) else m

    @property
    def shape(self):
        m = self._compute()
        return m.shape if isinstance(m, Tensor) else [t.shape for t in m]

    def numpy(self):
        m = self._compute()
        return m.numpy() if isinstance(m, Tensor) else m


class Hessian:
    """autograd.Hessian parity over a scalar-output function; is_batched
    treats the leading dim as a batch of independent samples ([B, N] input,
    per-sample scalar output → [B, N, N])."""

    def __init__(self, func, xs, is_batched=False):
        self._func = func
        self._xs = xs if isinstance(xs, (list, tuple)) else [xs]
        self._is_batched = is_batched
        self._mat = None

    def _compute(self):
        if self._mat is None:
            raw = [_unwrap(x) for x in self._xs]

            if self._is_batched:
                if len(raw) != 1:
                    raise ValueError("batched Hessian supports one input")

                def single(row):
                    out = _pure(self._func)(row[None])
                    return jnp.ravel(out)[0]

                hess = jax.vmap(jax.hessian(single))(raw[0])
            else:
                def scalar(*a):
                    out = _pure(self._func)(*a)
                    return out.reshape(()) if hasattr(out, "reshape") else out

                hess = jax.hessian(scalar,
                                   argnums=tuple(range(len(raw))))(*raw)
                hess = hess[0][0] if len(raw) == 1 else hess
            self._mat = jax.tree_util.tree_map(
                lambda h: Tensor(h, _internal=True), hess)
        return self._mat

    def __getitem__(self, idx):
        return self._compute()[idx]

    @property
    def shape(self):
        m = self._compute()
        return m.shape if isinstance(m, Tensor) else None

    def numpy(self):
        return self._compute().numpy()


def forward_grad(outputs_fn, xs, v=None):
    """primapi.forward_grad parity: forward-mode gradient."""
    _, tangent = jvp(outputs_fn, xs, v)
    return tangent


def grad(func, xs, v=None):
    """Functional reverse grad of `func` at xs (primapi.grad parity)."""
    _, g = vjp(func, xs, v)
    return g


# -- prim-system toggles ------------------------------------------------------
# Reference: primapi/primx enable_prim()/disable_prim()/prim_enabled() switch
# static autodiff onto primitive-op lowering (orig2prim/prim2orig program
# passes).  jax traces through composable primitives ALWAYS, so the toggle
# holds state for API parity and reporting only.
_prim_enabled = [False]


def enable_prim():
    _prim_enabled[0] = True


def disable_prim():
    _prim_enabled[0] = False


def prim_enabled() -> bool:
    return _prim_enabled[0]


__all__ += ["enable_prim", "disable_prim", "prim_enabled"]
