"""ASP (Automatic SParsity) — parity with the reference incubate/asp/
(2:4 structured sparsity masks + OptimizerWithSparsityGuarantee; the CUDA
side uses cuSPARSELt, on TPU the mask is a plain elementwise multiply XLA
fuses into the consumer matmul).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor

__all__ = ["calculate_density", "create_mask", "check_mask_2d", "prune_model",
           "decorate", "OptimizerWithSparsityGuarantee", "reset_excluded_layers",
           "set_excluded_layers"]

_EXCLUDED: set = set()
# param id -> mask, filled by prune_model; consulted by every
# OptimizerWithSparsityGuarantee so decorate-before-prune (the reference's
# canonical order) still keeps sparsity after steps
_MASK_REGISTRY: dict = {}


def calculate_density(x) -> float:
    if isinstance(x, Tensor):
        # count on device: ONE scalar crosses the host boundary instead
        # of downloading the whole (possibly huge) parameter
        frac = (x != 0).astype("float32").mean()
        return float(frac.item())
    arr = np.asarray(x)
    return float((arr != 0).sum() / arr.size)


def create_mask(tensor, func_name="mask_2d_best", n=2, m=4):
    """2:4 (n-of-m) mask along the last dim: keep the n largest-|w| entries
    of every m-group.

    A Tensor input is masked entirely on device (rank within each m-group
    via a double argsort) and a device mask comes back — pruning a model
    no longer downloads every weight to the host and uploads the mask
    again, and XLA fuses the mask multiply into the consumer matmul.
    """
    if isinstance(tensor, Tensor):
        arr = tensor._value
        if arr.ndim < 1 or arr.shape[-1] % m:
            return jnp.ones_like(arr)
        groups = jnp.abs(arr).reshape(-1, m)
        order = jnp.argsort(-groups, axis=1)
        rank = jnp.argsort(order, axis=1)     # rank of each entry by |w|
        mask = (rank < n).astype(arr.dtype)
        return mask.reshape(arr.shape)
    arr = np.asarray(tensor)
    if arr.ndim < 1 or arr.shape[-1] % m:
        return np.ones_like(arr)
    groups = np.abs(arr).reshape(-1, m)
    order = np.argsort(-groups, axis=1)[:, :n]
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, order, 1.0, axis=1)
    return mask.reshape(arr.shape).astype(arr.dtype)


def check_mask_2d(mask, n=2, m=4) -> bool:
    arr = np.asarray(mask)
    if arr.shape[-1] % m:
        return False
    groups = arr.reshape(-1, m)
    return bool((groups.sum(axis=1) == n).all())


def set_excluded_layers(param_names, main_program=None):
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def _prunable(p) -> bool:
    return (not p.stop_gradient and p.name not in _EXCLUDED and
            len(p.shape) == 2 and p.shape[-1] % 4 == 0)


def prune_model(model, n=2, m=4, mask_algo="mask_2d_best", with_mask=True):
    """Apply n:m masks to every prunable 2-D weight; returns {name: mask}."""
    masks = {}
    for p in model.parameters():
        if not _prunable(p):
            continue
        mask = create_mask(p, n=n, m=m)
        p._replace_(p._value * jnp.asarray(mask), None)
        masks[p.name] = mask
        _MASK_REGISTRY[id(p)] = jnp.asarray(mask)
    return masks


class OptimizerWithSparsityGuarantee:
    """Reference ASPHelper.decorate result: after each optimizer step the
    masks are re-applied so pruned entries stay zero."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._masks = {}  # id(param) -> jnp mask

    def _register(self, masks_by_param):
        self._masks = {id(p): jnp.asarray(m) for p, m in masks_by_param}

    def step(self):
        self._optimizer.step()
        for p in self._optimizer._parameters:
            mask = self._masks.get(id(p))
            if mask is None:
                mask = _MASK_REGISTRY.get(id(p))
            if mask is not None:
                p._replace_(p._value * mask, None)

    def __getattr__(self, name):
        return getattr(self.__dict__["_optimizer"], name)


def decorate(optimizer, model=None, n=2, m=4):
    """asp.decorate parity: wrap the optimizer; if `model` is given, prune it
    now and register the masks."""
    wrapped = OptimizerWithSparsityGuarantee(optimizer)
    if model is not None:
        masks = prune_model(model, n=n, m=m)
        by_param = [(p, masks[p.name]) for p in model.parameters()
                    if p.name in masks]
        wrapped._register(by_param)
    return wrapped
