"""DistributedFusedLamb — parity with incubate/optimizer/
distributed_fused_lamb.py:86.

The reference's CUDA kernel (operators/optimizers/distributed_fused_lamb_op.cu)
flattens all params into one buffer, shards the LAMB math across ranks and
allgathers results.  Under GSPMD the same schedule falls out of running the
regular Lamb update with ZeRO-sharded slots (spmd.ShardedTrainStep,
sharding_stage>=1), so this class is Lamb tagged for slot sharding — the
compiled step does the shard/allgather.
"""
from __future__ import annotations

from ...optimizer.optimizer import Lamb


class DistributedFusedLamb(Lamb):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 use_master_param_norm=True, gradient_accumulation_steps=1,
                 use_master_acc_grad=True, nproc_per_node=None, name=None):
        super().__init__(learning_rate=learning_rate,
                         lamb_weight_decay=lamb_weight_decay,
                         beta1=beta1, beta2=beta2, epsilon=epsilon,
                         parameters=parameters, grad_clip=grad_clip,
                         exclude_from_weight_decay_fn=exclude_from_weight_decay_fn)
        # consumed by ShardedTrainStep: shard LAMB state over the sharding axis
        self._sharding_stage = 1
        self.gradient_accumulation_steps = gradient_accumulation_steps
