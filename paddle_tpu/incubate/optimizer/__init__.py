"""paddle.incubate.optimizer parity (SURVEY §2.3 incubate:
DistributedFusedLamb at incubate/optimizer/distributed_fused_lamb.py:86,
LookAhead, ModelAverage)."""
from .lookahead import LookAhead  # noqa: F401
from .modelaverage import ModelAverage  # noqa: F401
from .distributed_fused_lamb import DistributedFusedLamb  # noqa: F401
