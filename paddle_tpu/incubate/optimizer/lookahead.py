"""LookAhead optimizer — parity with incubate/optimizer/lookahead.py:
slow weights track fast weights every k steps
(slow += alpha * (fast - slow); fast = slow)."""
from __future__ import annotations

import jax.numpy as jnp

from ...optimizer.optimizer import Optimizer


class LookAhead(Optimizer):
    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not isinstance(inner_optimizer, Optimizer):
            raise TypeError("inner_optimizer must be an Optimizer")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._parameters = inner_optimizer._parameters
        self._grad_clip = None
        # slow weights start at the INITIAL fast weights (reference
        # lookahead.py), so the first k-step sync really interpolates
        self._slow = {id(p): p._value for p in self._parameters}
        self._lookahead_step = 0
        self._step_count = 0
        self._lr = inner_optimizer._lr

    def step(self):
        self.inner_optimizer.step()
        self._step_count = self.inner_optimizer._step_count
        self._lookahead_step += 1
        if self._lookahead_step % self.k == 0:
            for p in self._parameters:
                slow = self._slow[id(p)]
                slow = slow + self.alpha * (p._value - slow)
                self._slow[id(p)] = slow
                p._replace_(slow, None)

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def set_lr(self, lr):
        return self.inner_optimizer.set_lr(lr)

    def minimize(self, loss, *a, **kw):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["@lookahead_step"] = self._lookahead_step
        return sd

    def set_state_dict(self, sd):
        self._lookahead_step = int(sd.pop("@lookahead_step", 0)) \
            if isinstance(sd, dict) else 0
        self.inner_optimizer.set_state_dict(sd)
