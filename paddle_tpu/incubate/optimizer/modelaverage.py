"""ModelAverage — parity with incubate/optimizer/modelaverage.py: keeps a
running average of parameters over a sliding window; `apply()` swaps the
averaged weights in (restorable with `restore()`)."""
from __future__ import annotations

import contextlib

import jax.numpy as jnp


class ModelAverage:
    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self.rate = average_window_rate
        self.min_window = min_average_window
        self.max_window = max_average_window
        self._parameters = list(parameters or [])
        self._sum = {id(p): jnp.zeros_like(p._value)
                     for p in self._parameters}
        self._count = 0
        self._backup = None

    def step(self):
        """Accumulate current weights (call after optimizer.step())."""
        window = max(self.min_window,
                     min(self.max_window, int(self._count * self.rate) + 1))
        if self._count >= window:
            # restart the window (reference resets sums when exceeded)
            self._sum = {id(p): jnp.zeros_like(p._value)
                         for p in self._parameters}
            self._count = 0
        for p in self._parameters:
            self._sum[id(p)] = self._sum[id(p)] + p._value
        self._count += 1

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Swap in averaged weights within the context (no-op before any
        step() has accumulated — never zeroes the live weights)."""
        if self._count == 0:
            yield
            return
        self._backup = {id(p): p._value for p in self._parameters}
        for p in self._parameters:
            p._replace_(self._sum[id(p)] / self._count, None)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup is not None:
            for p in self._parameters:
                p._replace_(self._backup[id(p)], None)
            self._backup = None
