"""paddle.incubate.multiprocessing parity — share Tensors across python
processes.

Reference: python/paddle/incubate/multiprocessing/reductions.py (registers
ForkingPickler reduce functions so Tensors travel through mp.Queue /
Pipe via CUDA IPC handles or shared-memory files instead of pickled
copies).

TPU-native: device memory is not host-shareable, so tensors are staged
through POSIX shared memory (multiprocessing.shared_memory) on the host —
the same route the reference takes for CPU tensors (mmap files).  The
consumer re-materializes a device array lazily on first use.  API:

    import paddle_tpu.incubate.multiprocessing as mp
    q = mp.Queue()            # a context with tensor reductions installed
    q.put(tensor)             # zero-pickle-copy via shm

DELIVERY CONTRACT: each sent tensor is deserializable exactly ONCE — the
first consumer copies out of the segment and unlinks it (duplicated
delivery / multi-consumer fan-out must send one message per consumer).
Producer-side segments are bounded (64 in flight); segments evicted from
that window and any still live at exit are unlinked by an atexit hook, so
/dev/shm cannot leak past process lifetime.
"""
from __future__ import annotations

import atexit
import multiprocessing as _std_mp
from multiprocessing import shared_memory
from multiprocessing.reduction import ForkingPickler

import numpy as np

from ...core.tensor import Tensor

__all__ = ["init_reductions", "Queue", "Pipe", "Process", "get_context"]

_INITIALIZED = False
# keep producer-side segments alive until the consumer rebuilds (which
# unlinks); bounded window, see _reduce_tensor
_LIVE_SEGMENTS: list = []
# names evicted from the window whose consumers may not have rebuilt yet:
# unlinked at exit (an unconsumed name would otherwise survive the process
# in /dev/shm until reboot)
_EVICTED_NAMES: list = []


def _cleanup_segments():
    for shm in _LIVE_SEGMENTS:
        try:
            shm.close()
            shm.unlink()
        except Exception:
            pass
    for name in _EVICTED_NAMES:
        try:
            s = shared_memory.SharedMemory(name=name)
            s.close()
            s.unlink()
        except Exception:
            pass
    _LIVE_SEGMENTS.clear()
    _EVICTED_NAMES.clear()


atexit.register(_cleanup_segments)


def _np_dtype(name: str):
    """Resolve a dtype NAME — numpy's own, or an ml_dtypes extension
    (bfloat16, float8_*): dtype.str would be an opaque '<V2' for those."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _rebuild_tensor_from_shm(shm_name: str, shape, dtype_str: str,
                             stop_gradient: bool):
    shm = shared_memory.SharedMemory(name=shm_name)
    try:
        arr = np.ndarray(shape, dtype=_np_dtype(dtype_str),
                         buffer=shm.buf).copy()
    finally:
        shm.close()
        try:
            shm.unlink()
        except FileNotFoundError:
            pass
    t = Tensor(arr)
    t.stop_gradient = stop_gradient
    return t


def _reduce_tensor(t: Tensor):
    arr = np.asarray(t._value)
    shm = shared_memory.SharedMemory(create=True, size=max(1, arr.nbytes))
    dst = np.ndarray(arr.shape, dtype=arr.dtype, buffer=shm.buf)
    dst[...] = arr
    _LIVE_SEGMENTS.append(shm)
    if len(_LIVE_SEGMENTS) > 64:          # bounded producer-side cache
        old = _LIVE_SEGMENTS.pop(0)
        old.close()
        # consumer may already have rebuilt (then this name is gone and
        # the atexit unlink is a no-op); if not, the name is reclaimed at
        # process exit instead of leaking in /dev/shm
        _EVICTED_NAMES.append(old.name)
    return (_rebuild_tensor_from_shm,
            (shm.name, arr.shape, arr.dtype.name, t.stop_gradient))


def init_reductions() -> None:
    """Install the Tensor reducer on ForkingPickler (reductions.py
    init_reductions)."""
    global _INITIALIZED
    if _INITIALIZED:
        return
    ForkingPickler.register(Tensor, _reduce_tensor)
    _INITIALIZED = True


# -- thin context surface (reference re-exports multiprocessing with the
# reducers installed) --------------------------------------------------------
def get_context(method=None):
    init_reductions()
    return _std_mp.get_context(method)


def Queue(*args, **kwargs):
    init_reductions()
    return _std_mp.get_context("spawn").Queue(*args, **kwargs)


def Pipe(duplex=True):
    init_reductions()
    return _std_mp.get_context("spawn").Pipe(duplex)


def Process(*args, **kwargs):
    init_reductions()
    return _std_mp.get_context("spawn").Process(*args, **kwargs)
