from . import math  # noqa: F401
from .math import (segment_max, segment_mean, segment_min,  # noqa: F401
                   segment_sum)

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min"]
