"""paddle.incubate.tensor.math — segment reductions (reference:
python/paddle/incubate/tensor/math.py:28,92,158,224 — deprecated shims
pointing at paddle.geometric.segment_*, which is where ours live)."""
from ...geometric import (segment_max, segment_mean,  # noqa: F401
                          segment_min, segment_sum)

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min"]
