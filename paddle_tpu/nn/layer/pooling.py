"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .. import functional as F
from ..layer_base import Layer
from ..layout import resolve_data_format


class _Pool(Layer):
    def __init__(self, **kw):
        super().__init__()
        self._kw = {k: v for k, v in kw.items() if k != "name"}
        if "data_format" in self._kw:
            self._kw["data_format"] = resolve_data_format(
                self._kw["data_format"])


class MaxPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__(kernel_size=kernel_size, stride=stride, padding=padding,
                         return_mask=return_mask, ceil_mode=ceil_mode,
                         data_format="NCL")

    def forward(self, x):
        return F.max_pool1d(x, **self._kw)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__(kernel_size=kernel_size, stride=stride, padding=padding,
                         return_mask=return_mask, ceil_mode=ceil_mode,
                         data_format=data_format)

    def forward(self, x):
        return F.max_pool2d(x, **self._kw)


class MaxPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__(kernel_size=kernel_size, stride=stride, padding=padding,
                         return_mask=return_mask, ceil_mode=ceil_mode,
                         data_format=data_format)

    def forward(self, x):
        return F.max_pool3d(x, **self._kw)


class AvgPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__(kernel_size=kernel_size, stride=stride, padding=padding,
                         exclusive=exclusive, ceil_mode=ceil_mode,
                         data_format="NCL")

    def forward(self, x):
        return F.avg_pool1d(x, **self._kw)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__(kernel_size=kernel_size, stride=stride, padding=padding,
                         ceil_mode=ceil_mode, exclusive=exclusive,
                         divisor_override=divisor_override, data_format=data_format)

    def forward(self, x):
        return F.avg_pool2d(x, **self._kw)


class AvgPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__(kernel_size=kernel_size, stride=stride, padding=padding,
                         ceil_mode=ceil_mode, exclusive=exclusive,
                         divisor_override=divisor_override, data_format=data_format)

    def forward(self, x):
        return F.avg_pool3d(x, **self._kw)


class AdaptiveAvgPool1D(_Pool):
    def __init__(self, output_size, name=None):
        super().__init__(output_size=output_size, data_format="NCL")

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, **self._kw)


class AdaptiveAvgPool2D(_Pool):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__(output_size=output_size, data_format=data_format)

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, **self._kw)


class AdaptiveAvgPool3D(_Pool):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__(output_size=output_size, data_format=data_format)

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, **self._kw)


class AdaptiveMaxPool1D(_Pool):
    def __init__(self, output_size, return_mask=False, name=None):
        if return_mask:
            raise NotImplementedError(
                "AdaptiveMaxPool1D(return_mask=True) is not supported: the "
                "adaptive bins carry no window-argmax path")
        super().__init__(output_size=output_size, data_format="NCL")

    def forward(self, x):
        return F.adaptive_max_pool1d(x, **self._kw)


class AdaptiveMaxPool2D(_Pool):
    def __init__(self, output_size, return_mask=False, name=None):
        if return_mask:
            raise NotImplementedError(
                "AdaptiveMaxPool2D(return_mask=True) is not supported: the "
                "adaptive bins carry no window-argmax path")
        super().__init__(output_size=output_size, data_format="NCHW")

    def forward(self, x):
        return F.adaptive_max_pool2d(x, **self._kw)


class AdaptiveMaxPool3D(_Pool):
    def __init__(self, output_size, return_mask=False, name=None):
        if return_mask:
            raise NotImplementedError(
                "AdaptiveMaxPool3D(return_mask=True) is not supported: the "
                "adaptive bins carry no window-argmax path")
        super().__init__(output_size=output_size, data_format="NCDHW")

    def forward(self, x):
        return F.adaptive_max_pool3d(x, **self._kw)
