"""Common layers (reference: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer, ParamAttr
from ..layout import resolve_data_format


class Linear(Layer):
    """y = xW + b with W: [in_features, out_features] (reference layout)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = resolve_data_format(data_format)

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = resolve_data_format(data_format)

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = None if padding_idx is None else \
            (padding_idx if padding_idx >= 0 else num_embeddings + padding_idx)
        self._sparse = bool(sparse)
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.XavierUniform())
        # consumed by gather: FSDP/ZeRO-3 auto-sharding must leave this
        # table alone — GSPMD lowers gathers from a sharded table through a
        # full replicate-then-partition ("Involuntary full
        # rematerialization"), costing a [B,T,H] materialization per step
        self.weight._gather_indexed = True
        if self._padding_idx is not None and \
                hasattr(self.weight._value, "at"):  # skipped in abstract init
            self.weight._replace_(
                self.weight._value.at[self._padding_idx].set(0), None)

    def forward(self, x):
        from ...core import autograd
        if self._sparse and autograd.is_grad_enabled():
            import jax
            if not isinstance(self.weight._value, jax.core.Tracer):
                # eager: SelectedRows weight-grad (reference
                # Embedding(sparse=True) -> selected-rows lookup grad);
                # under trace the dense GSPMD path applies (see
                # core/selected_rows.py scope note)
                from ...core.selected_rows import sparse_embedding_lookup
                return sparse_embedding_lookup(self.weight, x,
                                               self._padding_idx)
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...ops.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        from ...ops.manipulation import reshape
        axis = self.axis % x.ndim
        new = list(x.shape[:axis]) + list(self.shape) + list(x.shape[axis + 1:])
        return reshape(x, new)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = resolve_data_format(data_format)

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = resolve_data_format(data_format)

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = resolve_data_format(data_format)

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = resolve_data_format(data_format)

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr)
        self.bias = self.create_parameter((1, out_features), attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        from ...ops.linalg import einsum
        out = einsum("bi,oij,bj->bo", x1, self.weight, x2)
        if self.bias is not None:
            out = out + self.bias
        return out


class _PadNd(Layer):
    def __init__(self, padding, mode, value, data_format):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = resolve_data_format(data_format)

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)
