"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

The time loop is a single `lax.scan` per layer/direction — compiled once by XLA
instead of the reference's per-step CUDA kernel launches or cuDNN RNN descriptors.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.op import apply_op
from ...core.tensor import Tensor
from .. import initializer as I
from ..layer_base import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        from ...ops.creation import full
        state_shape = self.state_shape
        if isinstance(state_shape[0], (list, tuple)):
            return tuple(full((batch,) + tuple(s), init_value) for s in state_shape)
        return full((batch,) + tuple(state_shape), init_value)


def _make_cell_params(layer, input_size, hidden_size, gate_mult, weight_ih_attr,
                      weight_hh_attr, bias_ih_attr, bias_hh_attr):
    std = 1.0 / np.sqrt(hidden_size)
    u = I.Uniform(-std, std)
    layer.weight_ih = layer.create_parameter(
        (gate_mult * hidden_size, input_size), attr=weight_ih_attr,
        default_initializer=u)
    layer.weight_hh = layer.create_parameter(
        (gate_mult * hidden_size, hidden_size), attr=weight_hh_attr,
        default_initializer=u)
    layer.bias_ih = layer.create_parameter(
        (gate_mult * hidden_size,), attr=bias_ih_attr, is_bias=True,
        default_initializer=u)
    layer.bias_hh = layer.create_parameter(
        (gate_mult * hidden_size,), attr=bias_hh_attr, is_bias=True,
        default_initializer=u)


def _simple_rnn_step(x, h, w_ih, w_hh, b_ih, b_hh, activation):
    z = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        z = z + b_ih + b_hh
    return jnp.tanh(z) if activation == "tanh" else jnp.maximum(z, 0)


def _lstm_step(x, h, c, w_ih, w_hh, b_ih, b_hh):
    z = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        z = z + b_ih + b_hh
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def _gru_step(x, h, w_ih, w_hh, b_ih, b_hh):
    xz = x @ w_ih.T + (b_ih if b_ih is not None else 0)
    hz = h @ w_hh.T + (b_hh if b_hh is not None else 0)
    xr, xu, xn = jnp.split(xz, 3, axis=-1)
    hr, hu, hn = jnp.split(hz, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    u = jax.nn.sigmoid(xu + hu)
    n = jnp.tanh(xn + r * hn)
    return (1 - u) * n + u * h


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        _make_cell_params(self, input_size, hidden_size, 1, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = apply_op(
            lambda x, h, wi, wh, bi, bh: _simple_rnn_step(
                x, h, wi, wh, bi, bh, self.activation),
            "simple_rnn_cell",
            (inputs, states, self.weight_ih, self.weight_hh, self.bias_ih,
             self.bias_hh), {})
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        _make_cell_params(self, input_size, hidden_size, 4, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states
        h_new, c_new = apply_op(
            lambda x, hh, cc, wi, wh, bi, bh: _lstm_step(x, hh, cc, wi, wh, bi, bh),
            "lstm_cell",
            (inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih,
             self.bias_hh), {})
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        _make_cell_params(self, input_size, hidden_size, 3, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = apply_op(
            lambda x, h, wi, wh, bi, bh: _gru_step(x, h, wi, wh, bi, bh),
            "gru_cell",
            (inputs, states, self.weight_ih, self.weight_hh, self.bias_ih,
             self.bias_hh), {})
        return out, out


def _scan_layer(mode, x, h0, c0, wi, wh, bi, bh, reverse, time_major):
    """One direction of one RNN layer as a lax.scan. x: [B, T, C] or [T, B, C]."""
    xs = x if time_major else jnp.swapaxes(x, 0, 1)
    if reverse:
        xs = jnp.flip(xs, axis=0)

    if mode == "LSTM":
        def step(carry, xt):
            h, c = carry
            h2, c2 = _lstm_step(xt, h, c, wi, wh, bi, bh)
            return (h2, c2), h2
        (hT, cT), ys = jax.lax.scan(step, (h0, c0), xs)
    elif mode == "GRU":
        def step(h, xt):
            h2 = _gru_step(xt, h, wi, wh, bi, bh)
            return h2, h2
        hT, ys = jax.lax.scan(step, h0, xs)
        cT = hT
    else:
        def step(h, xt):
            h2 = _simple_rnn_step(xt, h, wi, wh, bi, bh,
                                  "tanh" if mode == "RNN_TANH" else "relu")
            return h2, h2
        hT, ys = jax.lax.scan(step, h0, xs)
        cT = hT
    if reverse:
        ys = jnp.flip(ys, axis=0)
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, hT, cT


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirect else 1
        gate_mult = {"LSTM": 4, "GRU": 3}.get(mode, 1)
        std = 1.0 / np.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._all_weights = []
        for layer_i in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer_i == 0 else \
                    hidden_size * self.num_directions
                sfx = f"_reverse" if d else ""
                wi = self.create_parameter((gate_mult * hidden_size, in_sz),
                                           attr=weight_ih_attr, default_initializer=u)
                wh = self.create_parameter((gate_mult * hidden_size, hidden_size),
                                           attr=weight_hh_attr, default_initializer=u)
                bi = self.create_parameter((gate_mult * hidden_size,),
                                           attr=bias_ih_attr, is_bias=True,
                                           default_initializer=u)
                bh = self.create_parameter((gate_mult * hidden_size,),
                                           attr=bias_hh_attr, is_bias=True,
                                           default_initializer=u)
                self.add_parameter(f"weight_ih_l{layer_i}{sfx}", wi)
                self.add_parameter(f"weight_hh_l{layer_i}{sfx}", wh)
                self.add_parameter(f"bias_ih_l{layer_i}{sfx}", bi)
                self.add_parameter(f"bias_hh_l{layer_i}{sfx}", bh)
                self._all_weights.append((wi, wh, bi, bh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        batch_idx = 1 if self.time_major else 0
        batch = inputs.shape[batch_idx]
        n_state = self.num_layers * self.num_directions

        from ...ops.creation import zeros
        if initial_states is None:
            h0 = zeros((n_state, batch, self.hidden_size), dtype=inputs.dtype)
            c0 = zeros((n_state, batch, self.hidden_size), dtype=inputs.dtype)
        elif self.mode == "LSTM":
            h0, c0 = initial_states
        else:
            h0, c0 = initial_states, initial_states

        x = inputs
        h_outs, c_outs = [], []
        from .common import Dropout
        for layer_i in range(self.num_layers):
            dir_outs = []
            for d in range(self.num_directions):
                idx = layer_i * self.num_directions + d
                wi, wh, bi, bh = self._all_weights[idx]
                ys, hT, cT = apply_op(
                    lambda xv, h0v, c0v, wiv, whv, biv, bhv, _mode=self.mode,
                    _rev=bool(d), _tm=self.time_major: _scan_layer(
                        _mode, xv, h0v, c0v, wiv, whv, biv, bhv, _rev, _tm),
                    f"{self.mode.lower()}_layer",
                    (x, h0[idx], c0[idx], wi, wh, bi, bh), {})
                dir_outs.append(ys)
                h_outs.append(hT)
                c_outs.append(cT)
            if self.num_directions == 2:
                from ...ops.manipulation import concat
                x = concat(dir_outs, axis=-1)
            else:
                x = dir_outs[0]
            if self.dropout and layer_i < self.num_layers - 1 and self.training:
                from .. import functional as Fn
                x = Fn.dropout(x, p=self.dropout, training=True)
        from ...ops.manipulation import stack
        h_all = stack(h_outs, axis=0)
        if self.mode == "LSTM":
            c_all = stack(c_outs, axis=0)
            return x, (h_all, c_all)
        return x, h_all


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class RNN(Layer):
    """Wraps a cell into a recurrent layer (reference nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        time_axis = 0 if self.time_major else 1
        T = inputs.shape[time_axis]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = [None] * T
        for ti in steps:
            xt = inputs[ti] if self.time_major else inputs[:, ti]
            out, states = self.cell(xt, states)
            outs[ti] = out
        from ...ops.manipulation import stack
        return stack(outs, axis=time_axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        states_fw, states_bw = (initial_states if initial_states is not None
                                else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, states_fw)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw)
        from ...ops.manipulation import concat
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
