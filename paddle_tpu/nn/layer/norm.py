"""Normalisation layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer
from ..layout import resolve_data_format as _resolve_data_format


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = _resolve_data_format(data_format)
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """Legacy fluid BatchNorm (acts like BatchNorm1D/2D/3D by input rank)."""


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN.  Under GSPMD data parallelism the batch statistics are
    computed over the global (sharded) batch automatically when the step is
    jitted over the mesh, so this shares the BatchNorm implementation
    (reference: python/paddle/nn/layer/norm.py SyncBatchNorm + c_sync_calc ops)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for l in layer.sublayers(include_self=True):
            for name, sub in list(l._sub_layers.items()):
                if isinstance(sub, _BatchNormBase) and not isinstance(sub, SyncBatchNorm):
                    new = SyncBatchNorm(sub._num_features, sub._momentum,
                                        sub._epsilon, data_format=sub._data_format)
                    new.weight = sub.weight
                    new.bias = sub.bias
                    new.register_buffer("_mean", sub._mean)
                    new.register_buffer("_variance", sub._variance)
                    l._sub_layers[name] = new
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        self.weight = (None if weight_attr is False else self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True))

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={list(self._normalized_shape)}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = _resolve_data_format(data_format)
        self.weight = (None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr,
            default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True))

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self._args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self._args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.register_buffer("weight_u", Tensor(np.random.randn(h).astype(np.float32)))
        self.register_buffer("weight_v", Tensor(np.random.randn(w).astype(np.float32)))

    def forward(self, weight):
        return F.spectral_norm(weight, self.weight_u, self.weight_v, self._dim,
                               self._power_iters, self._epsilon)
