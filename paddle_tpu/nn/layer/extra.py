"""nn layer long tail — parity with reference nn/__init__ exports that
were still absent: Fold/Unfold, MaxUnPool1D/2D/3D, Softmax2D,
ThresholdedReLU, the distance/margin loss layers, HSigmoidLoss, and the
seq2seq BeamSearchDecoder/dynamic_decode pair (nn/decode.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..layer_base import Layer
from .. import functional as F
from ...core.tensor import Tensor

__all__ = ["Fold", "Unfold", "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
           "Softmax2D", "ThresholdedReLU", "PairwiseDistance",
           "SoftMarginLoss", "MultiLabelSoftMarginLoss",
           "TripletMarginWithDistanceLoss", "HSigmoidLoss",
           "BeamSearchDecoder", "dynamic_decode"]


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._args = (output_sizes, kernel_sizes, strides, paddings,
                      dilations)

    def forward(self, x):
        return F.fold(x, *self._args)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self._args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self._args)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self._kw = dict(kernel_size=kernel_size, stride=stride,
                        padding=padding, data_format=data_format,
                        output_size=output_size)

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, **self._kw)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self._kw = dict(kernel_size=kernel_size, stride=stride,
                        padding=padding, data_format=data_format,
                        output_size=output_size)

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, **self._kw)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self._kw = dict(kernel_size=kernel_size, stride=stride,
                        padding=padding, data_format=data_format,
                        output_size=output_size)

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, **self._kw)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW inputs (activation.Softmax2D)."""

    def forward(self, x):
        assert x.ndim in (3, 4), "Softmax2D expects 3D/4D input"
        return F.softmax(x, axis=-3)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self._kw = dict(p=p, epsilon=epsilon, keepdim=keepdim)

    def forward(self, x, y):
        return F.pairwise_distance(x, y, **self._kw)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self._reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self._reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self._weight,
                                              self._reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._kw = dict(distance_function=distance_function, margin=margin,
                        swap=swap, reduction=reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(input, positive,
                                                   negative, **self._kw)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid classifier head (nn/layer/loss.HSigmoidLoss):
    owns the [num_classes-1, feature_size] internal-node weights."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        self._num_classes = num_classes
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size), attr=weight_attr)
        self.bias = self.create_parameter((num_classes - 1, 1),
                                          attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self._num_classes,
                               self.weight, self.bias, path_table,
                               path_code)


# -- seq2seq decoding (reference nn/decode.py) -------------------------------

class BeamSearchDecoder:
    """Beam-search decoder over a step cell (reference
    nn/decode.py:BeamSearchDecoder).  The cell is any callable
    `cell(inputs, states) -> (logits_or_out, new_states)`; the embedding
    and output layers mirror the reference's `embedding_fn`/`output_fn`
    hooks.  Drive it with `dynamic_decode`."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # the eager protocol mirrors the reference's initialize/step/finalize
    def initialize(self, initial_states, batch_size):
        k = self.beam_size
        tokens = np.full((batch_size, k), self.start_token, np.int64)
        log_probs = np.full((batch_size, k), -1e9, np.float64)
        log_probs[:, 0] = 0.0   # only beam 0 live at t=0 (reference kNegInf)
        finished = np.zeros((batch_size, k), bool)
        return tokens, log_probs, finished, initial_states

    def step(self, tokens, states):
        import jax

        inp = Tensor(jnp.asarray(tokens.reshape(-1)), _internal=True)
        if self.embedding_fn is not None:
            inp = self.embedding_fn(inp)
        out, new_states = self.cell(inp, states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        logits = out._value if isinstance(out, Tensor) else jnp.asarray(out)
        return np.asarray(jax.nn.log_softmax(logits, axis=-1)), new_states


def _reorder_states(states, beam_src, b, k):
    """Gather every [b*k, ...] leaf of the cell state along the beam axis
    so hidden state stays paired with the beam that produced it."""
    import jax
    import jax.numpy as jnp

    idx = jnp.asarray(beam_src + np.arange(b)[:, None] * k).reshape(-1)

    def gather(leaf):
        val = leaf._value if isinstance(leaf, Tensor) else leaf
        if hasattr(val, "shape") and getattr(val, "ndim", 0) >= 1 \
                and val.shape[0] == b * k:
            out = jnp.asarray(val)[idx]
            return Tensor(out, _internal=True) if isinstance(leaf, Tensor) \
                else out
        return leaf

    return jax.tree_util.tree_map(
        gather, states,
        is_leaf=lambda x: isinstance(x, Tensor) or hasattr(x, "shape"))


def dynamic_decode(decoder, inits=None, max_step_num=None, batch_size=1,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Reference nn/decode.dynamic_decode: run the decoder until every
    beam finishes or max_step_num; returns (token ids [B, T, beam],
    final log-probs) (+ lengths)."""
    assert max_step_num is not None, "max_step_num is required"
    tokens, log_probs, finished, states = decoder.initialize(inits,
                                                             batch_size)
    b, k = tokens.shape
    history = []
    lengths = np.zeros((b, k), np.int64)
    for _ in range(int(max_step_num)):
        logp, states = decoder.step(tokens, states)
        v = logp.shape[-1]
        logp = logp.reshape(b, k, v)
        # finished beams only extend with end_token at zero cost
        end_only = np.full((v,), -1e9)
        end_only[decoder.end_token] = 0.0
        step_logp = np.where(finished[:, :, None], end_only[None, None],
                             logp)
        total = log_probs[:, :, None] + step_logp       # [b, k, v]
        flat = total.reshape(b, k * v)
        top = np.argsort(-flat, axis=1)[:, :k]          # [b, k]
        log_probs = np.take_along_axis(flat, top, axis=1)
        beam_src = top // v
        tokens = (top % v).astype(np.int64)
        # recurrent cell state must follow the surviving beams too: any
        # leaf with a leading b*k dim is gathered by beam_src
        states = _reorder_states(states, beam_src, b, k)
        finished = np.take_along_axis(finished, beam_src, axis=1) | (
            tokens == decoder.end_token)
        lengths = np.take_along_axis(lengths, beam_src, axis=1)
        lengths = lengths + (~finished).astype(np.int64)
        # reorder history to follow the surviving beams
        history = [np.take_along_axis(hst, beam_src, axis=1)
                   for hst in history]
        history.append(tokens)
        if finished.all():
            break
    out = np.stack(history, axis=1)                     # [b, T, k]
    if output_time_major:
        out = out.transpose(1, 0, 2)
    ids = Tensor(out)
    scores = Tensor(log_probs)
    if return_length:
        return ids, scores, Tensor(lengths)
    return ids, scores
