"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from ..layer_base import Layer
from .. import initializer as I


def _simple(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = dict(fixed)
            # positional args map onto the functional's keyword order
            fn = getattr(F, fn_name)
            import inspect
            params = [p for p in inspect.signature(fn).parameters][1:]
            for name, val in zip(params, args):
                self._kwargs[name] = val
            for k, v in kwargs.items():
                if k != "name":
                    self._kwargs[k] = v

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kwargs)

    _Act.__name__ = fn_name
    return _Act


ReLU = _simple("relu")
ReLU6 = _simple("relu6")
ELU = _simple("elu")
SELU = _simple("selu")
CELU = _simple("celu")
GELU = _simple("gelu")
Sigmoid = _simple("sigmoid")
LogSigmoid = _simple("log_sigmoid")
Hardsigmoid = _simple("hardsigmoid")
Hardswish = _simple("hardswish")
Hardtanh = _simple("hardtanh")
Hardshrink = _simple("hardshrink")
Softshrink = _simple("softshrink")
Tanhshrink = _simple("tanhshrink")
LeakyReLU = _simple("leaky_relu")
Softplus = _simple("softplus")
Softsign = _simple("softsign")
Silu = _simple("silu")
Swish = _simple("swish")
Mish = _simple("mish")
Tanh = _simple("tanh")
Softmax = _simple("softmax")
LogSoftmax = _simple("log_softmax")
Maxout = _simple("maxout")
GLU = _simple("glu")
RReLU = _simple("rrelu")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self.data_format)
