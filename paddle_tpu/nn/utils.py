"""paddle.nn.utils — parity with python/paddle/nn/utils/
(weight_norm_hook.py weight_norm/remove_weight_norm, spectral_norm_hook,
clip_grad_norm_/clip_grad_value_, transform_parameters.py
parameters_to_vector/vector_to_parameters).

Gradient correctness: the reparameterized weight is rebuilt each forward
FROM THE PARAMETERS with Tensor ops (eager-autograd-taped), so
weight_g/weight_v (and the spectral-normalized orig weight) receive
gradients — a raw-jnp recompute would silently freeze them."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from .layer_base import Parameter

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters"]


def _norm_except_t(v: Tensor, dim) -> Tensor:
    """||v|| reduced over every axis but `dim` (Tensor ops, taped)."""
    axes = [i for i in range(v.ndim) if i != dim]
    return (v * v).sum(axis=axes, keepdim=True).sqrt()


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize layer.<name> as g * v/||v|| (weight_norm_hook.py):
    registers <name>_g / <name>_v and rebuilds <name> in a forward
    pre-hook.  dim=None puts ONE scalar g over the whole tensor."""
    w = getattr(layer, name)
    ndim = w.ndim
    if dim is not None:
        dim = dim % ndim   # negative dims normalize like positive ones
    wv = w._value
    if dim is None:
        g0 = jnp.sqrt(jnp.sum(jnp.square(wv))).reshape(1)
    else:
        axes = tuple(i for i in range(ndim) if i != dim)
        g0 = jnp.sqrt(jnp.sum(jnp.square(wv), axis=axes)).reshape(-1)
    v = Parameter(jnp.copy(wv), name=f"{w.name}_v")
    g = Parameter(g0, name=f"{w.name}_g")
    del layer._parameters[name]
    if name in layer.__dict__:
        del layer.__dict__[name]
    layer.add_parameter(f"{name}_v", v)
    layer.add_parameter(f"{name}_g", g)
    layer._weight_norm_cfg = (name, dim)

    def _compute(lay):
        vv = getattr(lay, f"{name}_v")
        gg = getattr(lay, f"{name}_g")
        if dim is None:
            nrm = (vv * vv).sum().sqrt()
            wnew = vv * (gg.reshape([]) / nrm)
        else:
            nrm = _norm_except_t(vv, dim)
            shape = [1] * vv.ndim
            shape[dim] = -1
            wnew = vv / nrm * gg.reshape(shape)
        object.__setattr__(lay, name, wnew)

    _compute(layer)

    def pre_hook(lay, inputs):
        _compute(lay)
        return inputs

    layer._weight_norm_hook = layer.register_forward_pre_hook(pre_hook)
    return layer


def remove_weight_norm(layer, name="weight"):
    if not hasattr(layer, "_weight_norm_hook"):
        raise ValueError(f"weight_norm was not applied to {layer}")
    layer._weight_norm_hook.remove()
    nm, dim = layer._weight_norm_cfg
    v = getattr(layer, f"{name}_v")
    g = getattr(layer, f"{name}_g")
    if dim is None:
        nrm = jnp.sqrt(jnp.sum(jnp.square(v._value)))
        w = v._value * (g._value.reshape(()) / nrm)
    else:
        axes = tuple(i for i in range(v.ndim) if i != dim)
        nrm = jnp.sqrt(jnp.sum(jnp.square(v._value), axis=axes,
                               keepdims=True))
        shape = [1] * v.ndim
        shape[dim] = -1
        w = v._value / nrm * g._value.reshape(shape)
    del layer._parameters[f"{name}_v"]
    del layer._parameters[f"{name}_g"]
    if name in layer.__dict__:      # drop the taped shadow from the hook
        del layer.__dict__[name]
    layer.add_parameter(name, Parameter(w, name=nm))
    del layer._weight_norm_hook
    del layer._weight_norm_cfg
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Spectral normalization (spectral_norm_hook.py): each forward
    divides the CURRENT parameter (kept as <name>_orig) by its leading
    singular value.  The u/v power-iteration vectors are non-trainable
    state updated with raw values; sigma = u·W·v is computed with Tensor
    ops so the orig weight still trains."""
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    dim = dim % w.ndim
    del layer._parameters[name]
    if name in layer.__dict__:
        del layer.__dict__[name]
    layer.add_parameter(f"{name}_orig", w)

    wv = w._value
    mat0 = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
    rng = np.random.RandomState(0)
    u = jnp.asarray(rng.standard_normal(mat0.shape[0]), jnp.float32)
    u = u / jnp.maximum(jnp.linalg.norm(u), eps)
    v = mat0.astype(jnp.float32).T @ u
    v = v / jnp.maximum(jnp.linalg.norm(v), eps)
    state = {"u": u, "v": v}

    def _compute(lay):
        worig = getattr(lay, f"{name}_orig")
        val = worig._value
        m = jnp.moveaxis(val, dim, 0).reshape(val.shape[dim], -1
                                              ).astype(jnp.float32)
        u, v = state["u"], state["v"]
        for _ in range(n_power_iterations):
            v = m.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = m @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        state["u"], state["v"] = u, v
        # sigma differentiable wrt the param: u/v enter as constants
        perm = [dim] + [i for i in range(val.ndim) if i != dim]
        wm = worig.transpose(perm).reshape([val.shape[dim], -1])
        sigma = (Tensor(u[None, :], _internal=True).matmul(wm)
                 .matmul(Tensor(v[:, None], _internal=True))).reshape([])
        object.__setattr__(lay, name, worig / sigma)

    _compute(layer)

    def pre_hook(lay, inputs):
        _compute(lay)
        return inputs

    layer.register_forward_pre_hook(pre_hook)
    return layer


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """In-place global-norm gradient clip over eager grads
    (clip_grad_norm_.py); returns the total norm."""
    params = [p for p in (parameters if isinstance(parameters, (list, tuple))
                          else [parameters]) if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()), _internal=True)
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(p.grad._value)) for p in params]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(p.grad._value.astype(jnp.float64))
                     ** norm_type) for p in params])) ** (1.0 / norm_type)
    # opt-in error check: materializing the norm is the point (raise on a
    # host-visible non-finite value before the update applies)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):  # tpu-lint: ok(trace-hygiene)
        raise RuntimeError(
            "the total norm for gradients is non-finite; disable "
            "error_if_nonfinite to clip anyway")
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-12), 1.0)
    for p in params:
        p.grad._replace_((p.grad._value * scale).astype(
            p.grad._value.dtype), None)
    return Tensor(total, _internal=True)


def clip_grad_value_(parameters, clip_value):
    params = parameters if isinstance(parameters, (list, tuple)) \
        else [parameters]
    for p in params:
        if p.grad is not None:
            p.grad._replace_(
                jnp.clip(p.grad._value, -clip_value, clip_value), None)


def parameters_to_vector(parameters, name=None):
    return Tensor(jnp.concatenate(
        [p._value.reshape(-1) for p in parameters]), _internal=True)


def vector_to_parameters(vec, parameters, name=None):
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape)) if len(p.shape) else 1
        p._replace_(v[off:off + n].reshape(tuple(p.shape)).astype(
            p._value.dtype), None)
        off += n
