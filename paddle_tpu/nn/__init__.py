"""paddle.nn parity surface."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer_base import Layer, Parameter, ParamAttr  # noqa: F401
from .layout import channels_last, is_channels_last  # noqa: F401
from .meta import abstract_init, is_abstract_init  # noqa: F401
from .functional_call import functional_call, module_fn, state_values  # noqa: F401
from .clip import ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm  # noqa: F401
from .clip import clip_grad_norm_  # noqa: F401

from .layer.common import (  # noqa: F401
    Linear, Identity, Dropout, Dropout2D, Dropout3D, AlphaDropout, Embedding,
    Flatten, Unflatten, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D,
    PixelShuffle, PixelUnshuffle, ChannelShuffle, CosineSimilarity, Bilinear,
    Pad1D, Pad2D, Pad3D, ZeroPad2D,
)
from .layer.activation import (  # noqa: F401
    ReLU, ReLU6, ELU, SELU, CELU, GELU, Sigmoid, LogSigmoid, Hardsigmoid,
    Hardswish, Hardtanh, Hardshrink, Softshrink, Tanhshrink, LeakyReLU,
    Softplus, Softsign, Silu, Swish, Mish, Tanh, Softmax, LogSoftmax, Maxout,
    GLU, RReLU, PReLU,
)
from .layer.conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
)
from .layer.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm, LayerNorm,
    GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm,
)
from .layer.container import Sequential, LayerList, ParameterList, LayerDict  # noqa: F401
from .layer.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    KLDivLoss, SmoothL1Loss, MarginRankingLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss, TripletMarginLoss, CTCLoss,
)
from .layer.rnn import (  # noqa: F401
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN, LSTM,
    GRU,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layer.extra import (  # noqa: F401
    BeamSearchDecoder, Fold, HSigmoidLoss, MaxUnPool1D, MaxUnPool2D,
    MaxUnPool3D, MultiLabelSoftMarginLoss, PairwiseDistance, SoftMarginLoss,
    Softmax2D, ThresholdedReLU, TripletMarginWithDistanceLoss,
    dynamic_decode, Unfold,
)
from . import utils  # noqa: F401
from .utils import spectral_norm  # noqa: F401
from .layer import loss  # noqa: F401  (reference exports nn.loss)
from . import quant  # noqa: F401
