"""Channels-last construction mode — build any image model NHWC for TPU.

The reference keeps NCHW as the only model-zoo layout (its cuDNN kernels
prefer it).  TPU prefers channels-LAST: the channel dim lands on the
128-lane minor axis, so BatchNorm's per-channel reductions and the conv
epilogues vectorize without the layout copies NCHW forces (measured on
ResNet-50: the NCHW step spends ~2/3 of its device time in BN reduce /
apply passes and transposes, docs/PERF.md).

Usage::

    with paddle_tpu.nn.channels_last():
        model = resnet50()          # every image layer built as NHWC
    out = model(nhwc_images)        # inputs/outputs are channel-last

Inside the context every image layer constructed with a channel-FIRST
``data_format`` (the reference default) is flipped to its channel-last
equivalent; explicitly channel-last arguments pass through unchanged.
Parameter shapes are identical either way (conv weights stay OIHW), so
state dicts move freely between NCHW- and NHWC-built models.
"""
from __future__ import annotations

import contextlib
import threading

__all__ = ["channels_last", "is_channels_last", "resolve_data_format"]

_state = threading.local()

_TO_CHANNEL_LAST = {
    "NCHW": "NHWC",
    "NCL": "NLC",
    "NCDHW": "NDHWC",
}


def is_channels_last() -> bool:
    """True while inside a channels_last() construction context."""
    return getattr(_state, "on", False)


@contextlib.contextmanager
def channels_last(enable: bool = True):
    """Construction context: image layers default to channel-last layouts."""
    prev = getattr(_state, "on", False)
    _state.on = bool(enable)
    try:
        yield
    finally:
        _state.on = prev


def resolve_data_format(data_format: str) -> str:
    """Map a channel-first data_format to channel-last when constructing
    inside channels_last(); otherwise return it unchanged."""
    if data_format and is_channels_last():
        return _TO_CHANNEL_LAST.get(data_format, data_format)
    return data_format
