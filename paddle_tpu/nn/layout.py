"""Channels-last construction mode — build any image model NHWC for TPU.

The reference keeps NCHW as the only model-zoo layout (its cuDNN kernels
prefer it).  TPU prefers channels-LAST: the channel dim lands on the
128-lane minor axis, so BatchNorm's per-channel reductions and the conv
epilogues vectorize without the layout copies NCHW forces (measured on
ResNet-50: the NCHW step spends ~2/3 of its device time in BN reduce /
apply passes and transposes, docs/PERF.md).

Usage::

    with paddle_tpu.nn.channels_last():
        model = resnet50()          # every image layer built as NHWC
    out = model(nhwc_images)        # inputs/outputs are channel-last

Inside the context every image layer constructed with a channel-FIRST
``data_format`` (the reference default) is flipped to its channel-last
equivalent; explicitly channel-last arguments pass through unchanged.
Parameter shapes are identical either way (conv weights stay OIHW), so
state dicts move freely between NCHW- and NHWC-built models.
"""
from __future__ import annotations

import contextlib
import threading

__all__ = ["channels_last", "is_channels_last", "resolve_data_format"]

_state = threading.local()
# process-global default, set by paddle.incubate.autotune.set_config's
# layout domain: a thread-local alone would make the global autotune
# setting invisible to models built on worker threads
_global_on = False


def set_global_channels_last(flag: bool):
    global _global_on
    _global_on = bool(flag)


_TO_CHANNEL_LAST = {
    "NCHW": "NHWC",
    "NCL": "NLC",
    "NCDHW": "NDHWC",
}


def is_channels_last() -> bool:
    """True while inside a channels_last() construction context (this
    thread) or under the process-global autotune default."""
    return getattr(_state, "on", _global_on)


@contextlib.contextmanager
def channels_last(enable: bool = True):
    """Construction context: image layers default to channel-last layouts."""
    had = hasattr(_state, "on")
    prev = getattr(_state, "on", None)
    _state.on = bool(enable)
    try:
        yield
    finally:
        # restore EXACTLY: leaving a stale thread-local False behind would
        # permanently shadow the process-global autotune default
        if had:
            _state.on = prev
        else:
            del _state.on


def resolve_data_format(data_format: str) -> str:
    """Map a channel-first data_format to channel-last when constructing
    inside channels_last(); otherwise return it unchanged."""
    if data_format and is_channels_last():
        return _TO_CHANNEL_LAST.get(data_format, data_format)
    return data_format
