"""Functional execution of stateful Layers — the bridge to jit/grad/GSPMD.

The reference executes eagerly per-op (C++ dispatch) or rewrites a static
Program.  Here the compiled path works like torch.func.functional_call: swap
every Parameter/buffer value for a (possibly traced) value, run the Layer's
Python forward once under trace, read back mutated buffers.  Combined with
``jax.jit`` + shardings this replaces InterpreterCore, ParallelExecutor and the
202 fusion passes (XLA fuses).
"""
from __future__ import annotations

import contextlib
from typing import Any, Callable

import jax

from ..core import autograd
from ..core.tensor import Tensor


def state_values(layer) -> dict[str, Any]:
    """name → raw jax value for every parameter and persistable buffer."""
    return {k: v._value for k, v in layer.state_dict().items()}


def trainable_mask(layer) -> dict[str, bool]:
    mask = {}
    params = {id(p) for p in layer.parameters() if not p.stop_gradient}
    for k, v in layer.state_dict().items():
        mask[k] = id(v) in params
    return mask


@contextlib.contextmanager
def _swapped_state(layer, values: dict[str, Any]):
    entries = layer.state_dict()
    saved = {}
    for k, v in values.items():
        t = entries.get(k)
        if t is None:
            continue
        saved[k] = t._value
        t._value = v
    try:
        yield entries
    finally:
        for k, old in saved.items():
            entries[k]._value = old


def functional_call(layer, values: dict[str, Any], args=(), kwargs=None,
                    mutable_buffers: bool = True):
    """Run ``layer(*args)`` with parameter/buffer values taken from `values`.

    Returns (output, new_buffer_values) where new_buffer_values holds buffers
    mutated during the call (BN running stats) so a jitted caller can thread
    them through functionally.
    """
    kwargs = kwargs or {}
    with _swapped_state(layer, values) as entries:
        with autograd.no_grad():
            out = layer(*args, **kwargs)
        new_buffers = {}
        if mutable_buffers:
            param_ids = {id(p) for p in layer.parameters()}
            for k, t in entries.items():
                if id(t) not in param_ids and k in values \
                        and t._value is not values[k]:
                    new_buffers[k] = t._value
    return out, new_buffers


def module_fn(layer) -> Callable:
    """layer → pure fn(values, *raw_args) -> (raw_out, new_buffers)."""
    def fn(values, *raw_args):
        args = tuple(Tensor(a, _internal=True) if isinstance(a, jax.Array) or
                     hasattr(a, "dtype") else a for a in raw_args)
        out, new_buffers = functional_call(layer, values, args)
        raw_out = jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda x: isinstance(x, Tensor))
        return raw_out, new_buffers
    return fn
