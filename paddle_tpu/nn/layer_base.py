"""nn.Layer — the module base class.

Reference: python/paddle/fluid/dygraph/layers.py (1,749 LoC `Layer`): parameter /
buffer / sublayer registries, hooks, state_dict, train/eval.  Unlike the
reference there is no C++ VarBase underneath — parameters are Tensors holding
jax.Arrays, and the functional/jit path swaps their values for tracers via
``paddle_tpu.nn.functional_call``.
"""
from __future__ import annotations

import itertools
from collections import OrderedDict
from typing import Callable, Iterator

import numpy as np
import jax

from ..core.dtype import get_default_dtype, to_jax
from ..core.tensor import Tensor
from . import initializer as init_mod

_layer_counter = itertools.count()


class Parameter(Tensor):
    """Trainable tensor: stop_gradient=False by default (fluid framework.py
    `Parameter`)."""

    # _gather_indexed: the param is consumed by a gather (embedding table)
    # and must be exempt from FSDP auto-sharding (distributed/spmd.py
    # infer_param_specs)
    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip",
                 "is_distributed", "_gather_indexed")

    def __init__(self, data, dtype=None, name=None, trainable=True,
                 learning_rate=1.0, regularizer=None, need_clip=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": learning_rate}
        self.regularizer = regularizer
        self.need_clip = need_clip
        self.is_distributed = False
        self.persistable = True

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


class ParamAttr:
    """paddle.ParamAttr parity (python/paddle/fluid/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, init_mod.Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        raise TypeError(f"cannot make ParamAttr from {attr!r}")


class HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype=None):
        self._dtype = dtype or get_default_dtype()
        self._full_name = (name_scope or self.__class__.__name__.lower()) + \
            f"_{next(_layer_counter)}"
        self.training = True
        self._parameters: OrderedDict[str, Parameter] = OrderedDict()
        self._buffers: OrderedDict[str, Tensor] = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: OrderedDict[str, Layer] = OrderedDict()
        self._forward_pre_hooks: OrderedDict[int, Callable] = OrderedDict()
        self._forward_post_hooks: OrderedDict[int, Callable] = OrderedDict()
        self._hook_counter = itertools.count()

    # -- registration -------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning params")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        else:
            for d in (params, layers):
                if d is not None and name in d:
                    if value is None:
                        d.pop(name)
                    else:
                        raise TypeError(
                            f"cannot assign {type(value)} to registered slot {name!r}")
            if buffers is not None and name in buffers:
                if value is None or isinstance(value, Tensor):
                    buffers[name] = value
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for registry in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{self.__class__.__name__}' object has no attribute {name!r}")

    def __delattr__(self, name):
        for registry in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._buffers) + list(self._sub_layers)

    def add_parameter(self, name: str, parameter: Parameter | None):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Tensor | None, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Parameter | None:
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        from .meta import is_abstract_init
        if is_abstract_init():
            # meta construction: shape/dtype only, no initializer run
            import jax
            value = jax.ShapeDtypeStruct(tuple(int(s) for s in shape),
                                         to_jax(dtype))
        else:
            initializer = attr.initializer or default_initializer or (
                init_mod.Constant(0.0) if is_bias else init_mod.XavierUniform())
            value = initializer(tuple(int(s) for s in shape), to_jax(dtype))
        return Parameter(value, name=attr.name, trainable=attr.trainable,
                         learning_rate=attr.learning_rate,
                         regularizer=attr.regularizer, need_clip=attr.need_clip)

    def create_tensor(self, name=None, dtype=None, default_initializer=None):
        return Tensor(np.zeros((), dtype=dtype or self._dtype))

    # -- traversal ----------------------------------------------------------
    def parameters(self, include_sublayers=True) -> list[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[tuple[str, Parameter]]:
        seen = set()
        for name, layer_prefix, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield f"{layer_prefix}{pname}", p

    def _traverse(self, prefix="", include_sublayers=True):
        """Yield (unused, dotted-prefix, layer) for self and sublayers."""
        stack = [(prefix + "." if prefix else "", self)]
        seen = set()
        while stack:
            pfx, layer = stack.pop(0)
            if id(layer) in seen:
                continue
            seen.add(id(layer))
            yield (None, pfx, layer)
            if include_sublayers:
                for name, sub in layer._sub_layers.items():
                    if sub is not None:
                        stack.append((f"{pfx}{name}.", sub))

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for _, layer_prefix, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield f"{layer_prefix}{bname}", b

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def sublayers(self, include_self=False) -> list["Layer"]:
        out = []
        for _, _, layer in self._traverse():
            out.append(layer)
        return out if include_self else out[1:]

    def named_sublayers(self, prefix="", include_self=False):
        first = True
        for _, pfx, layer in self._traverse(prefix):
            if first and not include_self:
                first = False
                continue
            first = False
            yield pfx[:-1] if pfx.endswith(".") else pfx, layer

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def full_name(self):
        return self._full_name

    # -- mode ---------------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   use_hook=True, structured_name_prefix=""):
        dest = destination if destination is not None else OrderedDict()
        for _, pfx, layer in self._traverse(structured_name_prefix.rstrip("."),
                                            include_sublayers):
            for pname, p in layer._parameters.items():
                if p is not None:
                    dest[f"{pfx}{pname}"] = p
            for bname, b in layer._buffers.items():
                if b is not None and bname not in layer._non_persistable_buffer_names:
                    dest[f"{pfx}{bname}"] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        state_dict = dict(state_dict)
        # layers may define _state_dict_compat_(state, prefix) to migrate
        # legacy/foreign checkpoint layouts in place before matching
        for _, pfx, layer in self._traverse("", True):
            hook = getattr(layer, "_state_dict_compat_", None)
            if hook is not None:
                hook(state_dict, pfx)
        own = self.state_dict()
        missing, unexpected = [], []
        matched = {}
        for k, v in state_dict.items():
            if k in own:
                matched[k] = v
            else:
                unexpected.append(k)
        for k in own:
            if k not in matched:
                missing.append(k)
        for k, v in matched.items():
            target = own[k]
            val = v._value if isinstance(v, Tensor) else jax.numpy.asarray(np.asarray(v))
            if tuple(target.shape) != tuple(val.shape):
                raise ValueError(
                    f"shape mismatch for {k}: {tuple(target.shape)} vs {tuple(val.shape)}")
            target._replace_(val.astype(target._value.dtype), None)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        hid = next(self._hook_counter)
        self._forward_pre_hooks[hid] = hook
        return HookRemoveHelper(self._forward_pre_hooks, hid)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        hid = next(self._hook_counter)
        self._forward_post_hooks[hid] = hook
        return HookRemoveHelper(self._forward_post_hooks, hid)

    # -- misc ---------------------------------------------------------------
    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def to(self, device=None, dtype=None, blocking=None):
        for t in list(self.parameters()) + list(self.buffers()):
            moved = t.to(device, dtype)
            t._replace_(moved._value, None)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{self.__class__.__name__}({extra}" if extra
                 else f"{self.__class__.__name__}("]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            lines.append(f"  ({name}): " + "\n  ".join(sub_repr))
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 or not extra else \
            f"{self.__class__.__name__}({extra})"

    def extra_repr(self):
        return ""
