"""Abstract (meta) model construction — build a Layer tree whose parameters
are shape/dtype only, never materialized.

This is the AOT capacity-planning path: a GPT-3-6.7B-class model is far too
big to initialize on a dev host, but its train step can still be lowered,
compiled, and memory-analyzed for a target mesh
(`make_train_step(..., abstract=True).aot_compile(...)`) — plan the
v5e-16 recipe from a 1-core CPU box.  The reference has no analog; its
capacity planning is run-it-and-see on the cluster.

Usage::

    with paddle_tpu.nn.abstract_init():
        model = build_gpt("gpt3-6.7B-en")      # no bytes allocated
    step = dist.make_train_step(model, opt, mesh=mesh, abstract=True)
    mem = step.aot_compile(x_struct, y_struct).memory_analysis()
"""
from __future__ import annotations

import contextlib
import threading

__all__ = ["abstract_init", "is_abstract_init"]

_state = threading.local()


def is_abstract_init() -> bool:
    return getattr(_state, "on", False)


@contextlib.contextmanager
def abstract_init(enable: bool = True):
    prev = getattr(_state, "on", False)
    _state.on = bool(enable)
    try:
        yield
    finally:
        _state.on = prev
