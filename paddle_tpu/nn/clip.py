"""Gradient clipping (reference: python/paddle/fluid/clip.py —
ClipGradByValue/ByNorm/ByGlobalNorm)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.op import apply_op
from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, apply_op(
                lambda gv: jnp.clip(gv, self.min, self.max), "clip_by_value",
                (g,), {})))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue

            def impl(gv):
                norm = jnp.sqrt(jnp.sum(jnp.square(gv)))
                scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
                return gv * scale
            out.append((p, apply_op(impl, "clip_by_norm", (g,), {})))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _clip(self, params_grads):
        grads = [g for p, g in params_grads
                 if g is not None and getattr(p, "need_clip", True)]
        if not grads:
            return params_grads

        def global_norm_impl(*gs):
            return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                                for g in gs))
        gnorm = apply_op(global_norm_impl, "global_norm", tuple(grads), {})
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue

            def impl(gv, nv):
                scale = self.clip_norm / jnp.maximum(nv, self.clip_norm)
                return gv * scale.astype(gv.dtype)
            out.append((p, apply_op(impl, "clip_by_global_norm", (g, gnorm), {})))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    grads = [p.grad for p in params if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))

    def norm_impl(*gs):
        if norm_type == float("inf"):
            return jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in gs]))
        return jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g) ** norm_type) for g in gs])) ** (1.0 / norm_type)
    total = apply_op(norm_impl, "grad_norm", tuple(grads), {})

    # the clip coefficient stays on device (tpu-lint trace-hygiene: the
    # old float(total.item()) here was a blocking host round-trip per
    # step); clamping at 1.0 makes the no-clip case an exact *1.0
    def scale_impl(gv, tv):
        coef = jnp.minimum(max_norm / (tv + 1e-6), 1.0)
        return gv * coef.astype(gv.dtype)
    for p in params:
        if p.grad is not None:
            p.grad._replace_(apply_op(
                scale_impl, "grad_clip_scale", (p.grad, total), {})._value,
                None)
    return total
