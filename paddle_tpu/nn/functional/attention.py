"""Attention functionals.

Reference: the fused CUDA attention family (paddle/fluid/operators/fused/
fused_attention_op.cu, fmha_ref.h) materialises S×S scores; here the default is
a jnp reference implementation, and `scaled_dot_product_attention` routes to
the Pallas flash-attention kernel (paddle_tpu.kernels.flash_attention) on TPU
when shapes allow — the one place this framework hand-writes kernels.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.op import defop

_USE_FLASH = True


class FlashUnsupported(ValueError):
    """Raised by the flash routing when shape/mesh constraints rule the Pallas
    kernel out; the caller falls back to the dense reference silently (other
    exception types are real failures and warn loudly)."""


def enable_flash_attention(flag: bool):
    global _USE_FLASH
    _USE_FLASH = bool(flag)


def _sdpa_ref(q, k, v, mask, dropout_p, causal, scale, training):
    # q,k,v: [B, T, H, D] (paddle convention)
    qh = jnp.swapaxes(q, 1, 2)  # [B, H, T, D]
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        Tq, Tk = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((Tq, Tk), bool), k=Tk - Tq)
        scores = jnp.where(cm, scores, jnp.array(-1e30, scores.dtype))
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, jnp.array(-1e30, scores.dtype))
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p and training:
        from ...core import random as rnd
        keep = jax.random.bernoulli(rnd.next_key(), 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)  # [B, T, H, D]


def _flash_ok(q) -> bool:
    """Route to the Pallas kernel only on TPU, for non-trivial query lengths,
    and only when the sequence axis isn't sharded (flash needs the full K per
    shard; ring attention covers the 'sep'-sharded case)."""
    if not _USE_FLASH or q.shape[1] < 128:
        return False
    from ...distributed import mesh as mesh_mod
    if any(mesh_mod.axis_bound(a) for a in ("mp", "dcn", "dp", "sharding", "sep")):
        return False  # explicit shard_map mode: local shards, ref math
    mesh = mesh_mod.get_global_mesh()
    if mesh is not None and mesh.shape.get("sep", 1) > 1:
        return False
    try:
        import jax.extend.backend as jexb
        platform = jexb.get_backend().platform
    except Exception:
        platform = jax.default_backend()
    return platform not in ("cpu",)


def _flash_spmd(q, k, v, causal, scale):
    """Pallas call partitioned over the live mesh: batch over dp/sharding,
    heads over mp (a pallas_call is an opaque custom-call to GSPMD, so the
    partitioning must be made explicit with shard_map)."""
    from ...distributed import mesh as mesh_mod
    from jax.sharding import PartitionSpec as P
    from ...kernels.flash_attention import flash_attention_bthd

    mesh = mesh_mod.get_global_mesh()
    live = [a for a in ("dcn", "dp", "sharding", "mp")
            if mesh is not None and a in mesh.axis_names and
            mesh.shape.get(a, 1) > 1]
    if not live:
        return flash_attention_bthd(q, k, v, causal=causal, scale=scale)
    batch = tuple(a for a in ("dcn", "dp", "sharding") if a in live)
    heads = "mp" if "mp" in live else None
    n_batch = 1
    for a in batch:
        n_batch *= mesh.shape[a]
    if q.shape[0] % n_batch or (heads and q.shape[2] % mesh.shape["mp"]):
        raise FlashUnsupported("shapes not divisible by mesh axes")
    spec = P(batch if batch else None, None, heads, None)

    def local(qv, kv, vv):
        return flash_attention_bthd(qv, kv, vv, causal=causal, scale=scale)

    from ..._compat import shard_map
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


@defop
def fused_qkv_attention(qkv, dropout_p=0.0, is_causal=True, training=True,
                        name=None):
    """Self-attention on the FUSED head-major qkv tensor
    [batch, seq, heads, 3, head_dim] (the layout GPT/BERT qkv projections
    produce), returning [batch, seq, heads*head_dim].

    Purpose is performance: one whole-qkv transpose (which XLA fuses into
    the projection matmul) replaces the three per-operand layout copies the
    flash custom call otherwise forces, and the flat output feeds the row-
    parallel out-projection without another boundary copy (docs/PERF.md
    layout-copy tax; reference analog: fused_attention_op.cu keeps qkv fused
    for the same reason)."""
    b, t, nh, three, hd = qkv.shape
    scale = 1.0 / math.sqrt(hd)
    from ...distributed import mesh as mesh_mod
    if three == 3 and dropout_p == 0.0 and not mesh_mod.axis_bound("sep") \
            and _flash_ok(qkv) and qkv.shape[1] >= 128:
        try:
            return _fused_flash_spmd(qkv, is_causal, scale)
        except FlashUnsupported:
            pass
    q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
    if mesh_mod.axis_bound("sep"):
        if dropout_p and training:
            raise ValueError(
                "context parallelism (sep axis) supports only dropout-free "
                "attention; set attention_dropout_prob=0 or disable sep")
        from ...kernels.ring_attention import ring_attention
        out = ring_attention(q, k, v, axis_name="sep", causal=is_causal,
                             scale=scale)
    else:
        out = _sdpa_ref(q, k, v, None, dropout_p, is_causal, scale, training)
    return out.reshape(b, t, nh * hd)


def _fused_flash_spmd(qkv, causal, scale):
    """Flash path for the fused tensor, shard_map-partitioned when a mesh is
    live (batch over dp/sharding, heads over mp; output stays head-sharded
    on the flat hidden dim, which is exactly RowParallelLinear's
    input_is_parallel convention)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ...distributed import mesh as mesh_mod
    from ...kernels.flash_attention import flash_attention_qkv_fused

    b, t, nh, _, hd = qkv.shape

    def local(qkv5):
        bl, tl, nhl, _, hdl = qkv5.shape
        # ONE fused operand [BH, 3, T, D]: a single layout copy at the
        # custom-call boundary covers q, k and v
        qkvh = jnp.transpose(qkv5, (0, 2, 3, 1, 4)).reshape(
            bl * nhl, 3, tl, hdl)
        o3 = flash_attention_qkv_fused(qkvh, causal=causal, scale=scale)
        return jnp.transpose(o3.reshape(bl, nhl, tl, hdl),
                             (0, 2, 1, 3)).reshape(bl, tl, nhl * hdl)

    mesh = mesh_mod.get_global_mesh()
    live = [a for a in ("dcn", "dp", "sharding", "mp")
            if mesh is not None and a in mesh.axis_names and
            mesh.shape.get(a, 1) > 1]
    if not live:
        return local(qkv)
    batch = tuple(a for a in ("dcn", "dp", "sharding") if a in live)
    heads = "mp" if "mp" in live else None
    n_batch = 1
    for a in batch:
        n_batch *= mesh.shape[a]
    if qkv.shape[0] % n_batch or (heads and nh % mesh.shape["mp"]):
        raise FlashUnsupported("shapes not divisible by mesh axes")
    import jax
    in_spec = P(batch if batch else None, None, heads, None, None)
    out_spec = P(batch if batch else None, None, heads)
    from ..._compat import shard_map
    return shard_map(local, mesh=mesh, in_specs=(in_spec,),
                     out_specs=out_spec, check_vma=False)(qkv)


@defop
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """Inputs [batch, seq, heads, head_dim] like the reference fused op."""
    scale = 1.0 / math.sqrt(query.shape[-1])
    from ...distributed import mesh as mesh_mod
    if mesh_mod.axis_bound("sep"):
        # sequence axis is sharded (context parallelism): shard-local attention
        # would be globally wrong, so the ring path is mandatory here
        if attn_mask is not None or (dropout_p and training) or \
                query.shape[1] != key.shape[1]:
            raise ValueError(
                "context parallelism (sep axis) supports only mask-free, "
                "dropout-free self-attention with equal q/k lengths; set "
                "attention_dropout_prob=0 (or disable sep) — got "
                f"mask={attn_mask is not None}, dropout_p={dropout_p}, "
                f"tq={query.shape[1]}, tk={key.shape[1]}")
        from ...kernels.ring_attention import ring_attention
        return ring_attention(query, key, value, axis_name="sep",
                              causal=is_causal, scale=scale)
    if attn_mask is None and not (dropout_p and training) and \
            _flash_ok(query):
        try:
            return _flash_spmd(query, key, value, is_causal, scale)
        except FlashUnsupported:
            pass  # mesh-divisibility constraint: unfused reference path below
        except Exception as e:  # genuine backend/lowering failure: degrade
            import warnings    # loudly to the dense path rather than crash
            warnings.warn(f"flash attention path failed ({type(e).__name__}: "
                          f"{e}); falling back to dense reference attention")
    return _sdpa_ref(query, key, value, attn_mask, dropout_p, is_causal, scale,
                     training)


@defop
def fused_ln_linear(x, ln_weight, ln_bias, weight, bias=None, eps=1e-5,
                    name=None):
    """Pre-LN fused into its consuming projection: y = LN(x) @ weight
    (+ bias) as ONE pallas custom call (kernels/ln_matmul.py) — the LN
    boundary disappears into the matmul's operand read (docs/PERF.md:
    standalone LN boundaries lose; reference analog: the pre-LN fusion in
    fused_attention_op.cu / fused_feedforward_op.cu).  Falls back to the
    jnp composition when the kernel doesn't apply (CPU, unaligned dims)."""
    from ...distributed import mesh as _mesh_mod
    from ...kernels.ln_matmul import ln_matmul, ln_matmul_ok

    if ln_matmul_ok(x, weight,
                    mesh_free=_mesh_mod.get_global_mesh() is None):
        try:
            return ln_matmul(x, ln_weight, ln_bias, weight, bias, eps)
        except Exception as e:  # genuine lowering/compile failure: degrade
            import warnings    # loudly to the jnp composition (the same
            # contract as the flash paths above — an opt-in kernel must
            # never turn a training run into a crash)
            warnings.warn(f"ln_matmul kernel failed ({type(e).__name__}: "
                          f"{e}); falling back to jnp LN+matmul")
    # promote, never downcast: f64 inputs (x64 gradcheck mode) keep f64
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    d = xf - mu
    var = jnp.mean(d * d, axis=-1, keepdims=True)
    xln = ((d * jax.lax.rsqrt(var + eps)) * ln_weight + ln_bias) \
        .astype(x.dtype)
    y = jnp.matmul(xln, weight)
    return y if bias is None else y + bias
