"""Attention functionals.

Reference: the fused CUDA attention family (paddle/fluid/operators/fused/
fused_attention_op.cu, fmha_ref.h) materialises S×S scores; here the default is
a jnp reference implementation, and `scaled_dot_product_attention` routes to
the Pallas flash-attention kernel (paddle_tpu.kernels.flash_attention) on TPU
when shapes allow — the one place this framework hand-writes kernels.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.op import defop

_USE_FLASH = True


def enable_flash_attention(flag: bool):
    global _USE_FLASH
    _USE_FLASH = bool(flag)


def _sdpa_ref(q, k, v, mask, dropout_p, causal, scale, training):
    # q,k,v: [B, T, H, D] (paddle convention)
    qh = jnp.swapaxes(q, 1, 2)  # [B, H, T, D]
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        Tq, Tk = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((Tq, Tk), bool), k=Tk - Tq)
        scores = jnp.where(cm, scores, jnp.array(-1e30, scores.dtype))
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, jnp.array(-1e30, scores.dtype))
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p and training:
        from ...core import random as rnd
        keep = jax.random.bernoulli(rnd.next_key(), 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)  # [B, T, H, D]


@defop
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """Inputs [batch, seq, heads, head_dim] like the reference fused op."""
    scale = 1.0 / math.sqrt(query.shape[-1])
    if _USE_FLASH and attn_mask is None and not (dropout_p and training):
        try:
            from ...kernels.flash_attention import flash_attention_bthd
            return flash_attention_bthd(query, key, value, causal=is_causal,
                                        scale=scale)
        except Exception:
            pass
    return _sdpa_ref(query, key, value, attn_mask, dropout_p, is_causal, scale,
                     training)
