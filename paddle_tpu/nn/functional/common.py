"""Common functionals: linear/dropout/embedding/pad/interpolate/...
(reference: python/paddle/nn/functional/common.py, input.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import random as rnd
from ...core.dtype import get_default_dtype, to_jax
from ...core.op import defop, apply_op
from ...core.tensor import Tensor
from ...ops.manipulation import pad  # noqa: F401  (re-exported as F.pad)


@defop
def linear(x, weight, bias=None, name=None):
    # paddle stores Linear weights as [in_features, out_features]
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


@defop
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if mode == "upscale_in_train" else x * (1.0 - p)
    if p == 1.0:
        return jnp.zeros_like(x)
    shape = list(x.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = [s if i in [a % x.ndim for a in axes] else 1
                 for i, s in enumerate(shape)]
    keep = jax.random.bernoulli(rnd.next_key(), 1.0 - p, tuple(shape))
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0)
    return jnp.where(keep, x, 0.0)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


@defop
def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(rnd.next_key(), 1.0 - p, x.shape)
    a = (1.0 / np.sqrt((alpha_p ** 2 * p + 1) * (1 - p))).astype(np.float32)
    b = -a * alpha_p * p
    return a * jnp.where(keep, x, alpha_p) + b


@defop
def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


def one_hot(x, num_classes, name=None):
    from ...ops.creation import one_hot as _one_hot
    return _one_hot(x, num_classes)


@defop
def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    n = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / n


@defop
def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    nrm = jnp.sum(jnp.abs(x) ** p, axis=int(axis), keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(nrm, epsilon)


@defop
def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    dot = jnp.sum(x1 * x2, axis=int(axis))
    n1 = jnp.sqrt(jnp.sum(x1 * x1, axis=int(axis)))
    n2 = jnp.sqrt(jnp.sum(x2 * x2, axis=int(axis)))
    return dot / jnp.maximum(n1 * n2, eps)


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    if maxlen is None:
        maxlen = int(np.asarray(
            lengths._value if isinstance(lengths, Tensor) else lengths).max())
    return apply_op(
        lambda l: (jnp.arange(int(maxlen)) < l[..., None]).astype(to_jax(dtype)),
        "sequence_mask", (lengths,), {})


@defop
def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = int(upscale_factor)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = x.transpose(0, 1, 4, 2, 5, 3)
        return x.reshape(n, c // (r * r), h * r, w * r)
    # NHWC channels grouped [c_out, r, r] with c_out SLOWEST
    # (pixel_shuffle_kernel_impl.h:42 resize + {0,1,4,2,5,3} permute)
    n, h, w, c = x.shape
    co = c // (r * r)
    x = x.reshape(n, h, w, co, r, r)
    x = x.transpose(0, 1, 4, 2, 5, 3)
    return x.reshape(n, h * r, w * r, co)


@defop
def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = int(downscale_factor)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = x.transpose(0, 1, 3, 5, 2, 4)
        return x.reshape(n, c * r * r, h // r, w // r)
    # NHWC: output channels grouped [c, r, r] with c SLOWEST
    # (pixel_unshuffle_kernel_impl.h:42 resize + {0,1,3,5,2,4} permute) —
    # the exact inverse of the NHWC pixel_shuffle above
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    x = x.transpose(0, 1, 3, 5, 2, 4)
    return x.reshape(n, h // r, w // r, c * r * r)


@defop
def channel_shuffle(x, groups, data_format="NCHW", name=None):
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, groups, c // groups, h, w)
        return x.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, groups, c // groups)
    return x.transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)


@defop
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    if data_format in ("NCHW", "NCL", "NCDHW"):
        spatial = list(x.shape[2:])
        to_last = False
    else:
        spatial = list(x.shape[1:-1])
        to_last = True
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in np.asarray(size._value)]
        out_spatial = [int(s) for s in (size if isinstance(size, (list, tuple))
                                        else [size])]
    else:
        if isinstance(scale_factor, (list, tuple)):
            out_spatial = [int(s * f) for s, f in zip(spatial, scale_factor)]
        else:
            out_spatial = [int(s * scale_factor) for s in spatial]

    method = {"nearest": "nearest", "bilinear": "bilinear", "linear": "linear",
              "trilinear": "trilinear", "bicubic": "bicubic", "area": "linear"}[mode]
    if method in ("bilinear", "trilinear", "linear"):
        method = "linear"
    if to_last:
        out_shape = (x.shape[0], *out_spatial, x.shape[-1])
    else:
        out_shape = (x.shape[0], x.shape[1], *out_spatial)
    # jax.image.resize linear ≈ align_corners=False; nearest matches paddle default
    return jax.image.resize(x, out_shape, method=method)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


@defop(name="unfold_im2col")  # distinct registry key: Tensor.unfold (sliding
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    # window, ops/manipulation.py) already owns the plain "unfold" name
    """im2col (reference: phi unfold kernel): NCHW → [N, C*kh*kw, L]."""
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    dh, dw = pair(dilations)
    if isinstance(paddings, int):
        ph0 = ph1 = pw0 = pw1 = paddings
    elif len(paddings) == 2:
        (ph0, ph1), (pw0, pw1) = (paddings[0],) * 2, (paddings[1],) * 2
    else:
        ph0, pw0, ph1, pw1 = paddings
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)))
    oh = (h + ph0 + ph1 - (dh * (kh - 1) + 1)) // sh + 1
    ow = (w + pw0 + pw1 - (dw * (kw - 1) + 1)) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            sl = xp[:, :, i * dh:i * dh + (oh - 1) * sh + 1:sh,
                    j * dw:j * dw + (ow - 1) * sw + 1:sw]
            patches.append(sl)
    out = jnp.stack(patches, axis=2)  # N, C, kh*kw, oh, ow
    return out.reshape(n, c * kh * kw, oh * ow)


@defop
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = pair(output_sizes)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    dh, dw = pair(dilations)
    p = pair(paddings) if not isinstance(paddings, int) else (paddings, paddings)
    n, ckk, L = x.shape
    c = ckk // (kh * kw)
    ph, pw = p
    out_h = oh + 2 * ph
    out_w = ow + 2 * pw
    noh = (out_h - (dh * (kh - 1) + 1)) // sh + 1
    now = (out_w - (dw * (kw - 1) + 1)) // sw + 1
    xr = x.reshape(n, c, kh, kw, noh, now)
    out = jnp.zeros((n, c, out_h, out_w), dtype=x.dtype)
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, :, i * dh:i * dh + (noh - 1) * sh + 1:sh,
                         j * dw:j * dw + (now - 1) * sw + 1:sw].add(xr[:, :, i, j])
    return out[:, :, ph:ph + oh, pw:pw + ow]


def class_center_sample(label, num_classes, num_samples, group=None):
    """PartialFC class-center sampling (reference
    class_center_sample_op.cu / nn/functional/common.py:1850): keep every
    positive class center, sample negatives up to num_samples, return
    (remapped_label, sorted sampled class ids).  Dynamic output shape →
    host-side op feeding the margin-softmax's gathered centers.

    group=False / single-process group: local sampling (the supported
    scope; a real multi-rank group would need the cross-rank allgather of
    positives, which this build routes through mp_ops when a bound mesh
    axis exists)."""
    import numpy as np

    from ...core.tensor import Tensor

    if group not in (None, False) and getattr(group, "nranks", 1) > 1:
        raise NotImplementedError(
            "class_center_sample across a multi-rank group is not "
            "supported in-process; shard class centers with "
            "VocabParallelEmbedding + mp_ops instead")
    lab = np.asarray(label.numpy() if isinstance(label, Tensor)
                     else label).reshape(-1).astype(np.int64)
    if lab.size and (lab.min() < 0 or lab.max() >= num_classes):
        raise ValueError(
            f"labels must lie in [0, {num_classes}), got range "
            f"[{lab.min()}, {lab.max()}]")
    pos = np.unique(lab)
    if len(pos) < num_samples:
        neg_pool = np.setdiff1d(np.arange(num_classes, dtype=np.int64),
                                pos, assume_unique=True)
        # persistent stream (advances per call): identical batches must
        # still draw fresh negatives each epoch, like the reference kernel
        from ...geometric.sampling import _module_rng
        rng = _module_rng()
        k = min(num_samples - len(pos), len(neg_pool))
        chosen = rng.choice(neg_pool, size=k, replace=False)
        sampled = np.sort(np.concatenate([pos, chosen]))
    else:
        sampled = pos  # all positives kept (may exceed num_samples)
    remapped = np.searchsorted(sampled, lab)
    return (Tensor(remapped.astype(np.int64)),
            Tensor(sampled.astype(np.int64)))
