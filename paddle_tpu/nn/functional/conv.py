"""Convolution functionals on lax.conv_general_dilated — XLA tiles these onto
the MXU (reference: python/paddle/nn/functional/conv.py → phi conv kernels).

Layout note: the reference defaults to NCHW; XLA:TPU internally prefers NHWC
and transposes as needed, so we keep the user-facing NCHW contract and let the
compiler pick layouts.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.op import defop


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    return v * n if len(v) == 1 else v


def _norm_padding(padding, n):
    """paddle padding spec → lax [(lo, hi)] * n, or the string codes."""
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        # may include batch/channel dims ([[0,0],[0,0],[lo,hi],...])
        if len(padding) == n + 2:
            padding = padding[2:]
        return [tuple(p) for p in padding]
    raise ValueError(f"bad padding spec {padding}")


_POINTWISE_AS_DOT = False


def pointwise_as_dot(flag: bool):
    """Toggle the 1x1-conv->dot_general lowering (measured A/B on ResNet-50,
    docs/PERF.md: the dot form wins in isolation but loses ~2 ms/step in
    model context to backward-side layout fixups)."""
    global _POINTWISE_AS_DOT
    _POINTWISE_AS_DOT = bool(flag)


def _pointwise_conv(x, weight, stride, pad, groups, n, channel_last):
    """1x1 conv as dot_general when it is one (kernel 1, pad 0, groups 1).

    TPU rationale (measured, docs/PERF.md): lax.conv on kxk=1 kernels gets
    [O,I,1,1] weight layouts whose unit minor dims waste up to 128x of each
    lane tile — the momentum/Adam update fusions on those weights cost
    ~340us apiece — and the conv op itself trails XLA's dot pipelines.
    Contracting C with a [O,C]-reshaped weight fixes the weight layout for
    every consumer (optimizer included) and runs on the tuned MXU matmul
    path.  Strides subsample the input FIRST (less matmul work, exact same
    result for k=1)."""
    if not _POINTWISE_AS_DOT:
        return None
    if groups != 1 or isinstance(pad, str) or any(p != (0, 0) for p in pad):
        return None
    if any(weight.shape[2 + i] != 1 for i in range(n)):
        return None
    w2 = weight.reshape(weight.shape[0], weight.shape[1])  # [O, C]
    if any(s != 1 for s in stride):
        sl = [slice(None)] * x.ndim
        for i, s in enumerate(stride):
            sl[(1 if channel_last else 2) + i] = slice(None, None, s)
        x = x[tuple(sl)]
    cdim = x.ndim - 1 if channel_last else 1
    out = jax.lax.dot_general(x, w2, (((cdim,), (1,)), ((), ())))
    if not channel_last:
        out = jnp.moveaxis(out, -1, 1)
    return out


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, n,
             channel_last, transpose=False, output_padding=0, output_size=None):
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    pad = _norm_padding(padding, n)

    if channel_last:
        spec_in = "N" + "DHW"[3 - n:] + "C"
    else:
        spec_in = "NC" + "DHW"[3 - n:]
    spec_out = spec_in
    # weight layout: paddle conv weights are [out_c, in_c/groups, *k];
    # conv_transpose weights are [in_c, out_c/groups, *k]
    spec_w = ("IO" if transpose else "OI") + "DHW"[3 - n:]
    dn = jax.lax.conv_dimension_numbers(x.shape, weight.shape,
                                        (spec_in, spec_w, spec_out))
    if transpose:
        opad = _tuplize(output_padding, n)
        # transposed conv == gradient-of-conv: spatially flipped kernel with
        # swapped I/O (the IO spec swaps; flip here), input dilated by stride.
        spatial_axes = tuple(range(2, 2 + n))
        w = jnp.flip(weight, axis=spatial_axes)
        k = [weight.shape[2 + i] for i in range(n)]
        if isinstance(pad, str):
            p = [(0, 0)] * n if pad == "VALID" else [((k[i] - 1) // 2,) * 2
                                                     for i in range(n)]
        else:
            p = pad
        lax_pad = [((k[i] - 1) * dilation[i] - p[i][0],
                    (k[i] - 1) * dilation[i] - p[i][1] + opad[i])
                   for i in range(n)]
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=(1,) * n, padding=lax_pad,
            lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
    else:
        out = _pointwise_conv(x, weight, stride, pad, groups, n, channel_last)
        if out is None:
            out = jax.lax.conv_general_dilated(
                x, weight, window_strides=stride, padding=pad,
                rhs_dilation=dilation, dimension_numbers=dn,
                feature_group_count=groups)
    if bias is not None:
        if channel_last:
            out = out + bias.reshape((1,) * (n + 1) + (-1,))
        else:
            out = out + bias.reshape((1, -1) + (1,) * n)
    return out


@defop
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1,
                    channel_last=data_format == "NLC")


@defop
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    channel_last=data_format == "NHWC")


@defop
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    channel_last=data_format == "NDHWC")


@defop
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL",
                     name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1,
                    channel_last=data_format == "NLC", transpose=True,
                    output_padding=output_padding, output_size=output_size)


@defop
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW",
                     name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    channel_last=data_format == "NHWC", transpose=True,
                    output_padding=output_padding, output_size=output_size)


@defop
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW",
                     name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    channel_last=data_format == "NDHWC", transpose=True,
                    output_padding=output_padding, output_size=output_size)
