"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...core.op import defop, apply_op
from ...core.tensor import Tensor


def _reduce(val, reduction):
    if reduction == "mean":
        return jnp.mean(val)
    if reduction == "sum":
        return jnp.sum(val)
    return val


def _flcel_chunks(w, chunk):
    """Pad [V, H] to a whole number of `chunk` rows → ([n, chunk, H], V)."""
    V = w.shape[0]
    n = -(-V // chunk)
    pad = n * chunk - V
    if pad:
        w = jnp.pad(w, ((0, pad), (0, 0)))
    return w.reshape(n, chunk, w.shape[-1]), V


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_linear_nll(h, w, labels, ignore_index, chunk):
    nll, _ = _flcel_fwd_impl(h, w, labels, ignore_index, chunk)
    return nll


def _flcel_fwd_impl(h, w, labels, ignore_index, chunk):
    """Online-logsumexp over vocab chunks: the [N, V] logits tensor never
    materializes (the whole point — at GPT/BERT scale it is GBs of HBM
    traffic per pass; docs/PERF.md round-5 BERT section)."""
    wc, V = _flcel_chunks(w, chunk)
    n_chunks = wc.shape[0]
    N = h.shape[0]
    valid = labels != ignore_index
    safe_lab = jnp.where(valid, labels, 0)

    def body(carry, inp):
        m, s, tgt = carry
        ci, w_c = inp
        c0 = ci * chunk
        logits = jax.lax.dot_general(
            h, w_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [N, chunk] f32
        col_ok = (c0 + jnp.arange(chunk)) < V
        logits = jnp.where(col_ok[None, :], logits, -jnp.inf)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.exp(
            logits - m_new[:, None]).sum(axis=-1)
        off = safe_lab - c0
        in_c = (off >= 0) & (off < chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(off, 0, chunk - 1)[:, None], axis=-1)[:, 0]
        tgt = jnp.where(in_c, picked, tgt)
        return (m_new, s, tgt), None

    m0 = jnp.full((N,), -jnp.inf, jnp.float32)
    (m, s, tgt), _ = jax.lax.scan(
        body, (m0, jnp.zeros((N,), jnp.float32),
               jnp.zeros((N,), jnp.float32)),
        (jnp.arange(n_chunks), wc))
    lse = m + jnp.log(s)
    nll = jnp.where(valid, lse - tgt, 0.0)
    return nll, lse


def _flcel_fwd(h, w, labels, ignore_index, chunk):
    nll, lse = _flcel_fwd_impl(h, w, labels, ignore_index, chunk)
    return nll, (h, w, labels, lse)


def _flcel_bwd(ignore_index, chunk, res, g):
    h, w, labels, lse = res
    wc, V = _flcel_chunks(w, chunk)
    n_chunks = wc.shape[0]
    valid = labels != ignore_index
    gv = jnp.where(valid, g, 0.0).astype(jnp.float32)
    safe_lab = jnp.where(valid, labels, 0)

    def body(dh, inp):
        ci, w_c = inp
        c0 = ci * chunk
        logits = jax.lax.dot_general(
            h, w_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        col_ok = (c0 + jnp.arange(chunk)) < V
        logits = jnp.where(col_ok[None, :], logits, -jnp.inf)
        p = jnp.exp(logits - lse[:, None])               # softmax chunk
        off = safe_lab - c0
        onehot = ((off[:, None] == jnp.arange(chunk)[None, :]) &
                  valid[:, None])
        gl = (p - onehot) * gv[:, None]                  # dlogits [N, chunk]
        gl = jnp.where(col_ok[None, :], gl, 0.0).astype(h.dtype)
        dh = dh + gl @ w_c.astype(h.dtype)
        dw_c = jax.lax.dot_general(
            gl, h, (((0,), (0,)), ((), ())))             # [chunk, H]
        return dh, dw_c

    dh0 = jnp.zeros_like(h)
    dh, dw = jax.lax.scan(body, dh0, (jnp.arange(n_chunks), wc))
    dw = dw.reshape(n_chunks * chunk, -1)[:w.shape[0]].astype(w.dtype)
    return dh, dw, None


_fused_linear_nll.defvjp(_flcel_fwd, _flcel_bwd)


def fused_linear_nll_loss(hidden, weight, labels, ignore_index=-100,
                          transpose_weight=True, chunk_size=8192):
    """Fused LM-head + NLL over vocab chunks (round 5): computes
    nll = logsumexp(h @ Wᵀ) - (h @ Wᵀ)[label] WITHOUT materializing the
    [.., V] logits — online logsumexp forward, chunked softmax-recompute
    backward (one extra head matmul, the standard remat trade for ~5
    full passes of [N, V] HBM traffic).  `weight` is [V, H] when
    transpose_weight (the tied-embedding convention) else [H, V]."""
    def raw(h, w, lb):
        if not transpose_weight:
            w = w.T
        shape = h.shape[:-1]
        nll = _fused_linear_nll(h.reshape(-1, h.shape[-1]), w,
                                lb.reshape(-1), ignore_index, chunk_size)
        return nll.reshape(shape)

    return apply_op(raw, "fused_linear_nll_loss",
                    (hidden, weight, labels), {})


def fused_nll_loss(logits, labels, ignore_index=-100):
    """Fused logsumexp-gather NLL over the last axis: per-position losses
    [..., ] in fp32, zeros at ignored labels.

    Never materializes the [..., V] log-softmax (or an fp32 logits copy) —
    on TPU this recovers the whole LM loss-head cost (the fused form matches
    the no-loss throughput ceiling on the GPT bench).  Ignored positions use
    `where`, so NaN/Inf rows with ignore_index labels can't poison the loss.
    """
    def raw(lg, lb):
        lse = jax.nn.logsumexp(lg.astype(jnp.float32), axis=-1)
        valid = lb != ignore_index
        safe = jnp.where(valid, lb, 0)
        tgt = jnp.take_along_axis(
            lg, safe[..., None], axis=-1)[..., 0].astype(jnp.float32)
        return jnp.where(valid, lse - tgt, 0.0)

    return apply_op(raw, "fused_nll_loss", (logits, labels), {})


@defop
def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    logits = input
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.clip(logits, 1e-15, 1.0))
    n_classes = logits.shape[axis]

    if soft_label:
        lbl = label
        if label_smoothing > 0.0:
            lbl = (1 - label_smoothing) * lbl + label_smoothing / n_classes
        loss = -jnp.sum(lbl * logp, axis=axis)
        if weight is not None:
            w = jnp.sum(lbl * weight, axis=axis)
            loss = loss * w
        return _reduce(loss, reduction)

    lbl = label
    if lbl.ndim == logp.ndim:
        lbl = jnp.squeeze(lbl, axis=axis)
    lbl_i = lbl.astype(jnp.int32)
    valid = lbl_i != ignore_index
    safe = jnp.where(valid, lbl_i, 0)
    picked = jnp.take_along_axis(
        logp, jnp.expand_dims(safe, axis=axis), axis=axis)
    picked = jnp.squeeze(picked, axis=axis)
    if label_smoothing > 0.0:
        smooth = jnp.mean(logp, axis=axis)
        picked = (1 - label_smoothing) * picked + label_smoothing * smooth
    loss = -picked
    if weight is not None:
        w = jnp.take(weight, safe)
        loss = loss * w
        if reduction == "mean":
            denom = jnp.sum(jnp.where(valid, w, 0.0))
            return jnp.sum(jnp.where(valid, loss, 0.0)) / jnp.maximum(denom, 1e-12)
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss_t = loss if isinstance(loss, Tensor) else Tensor(loss)
    # reference keeps the reduced axis: unsqueeze back
    from ...ops.manipulation import unsqueeze
    loss_t = unsqueeze(loss_t, axis)
    if return_softmax:
        from .activation import softmax as softmax_fn
        return loss_t, softmax_fn(logits, axis=axis)
    return loss_t


@defop
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
             name=None):
    lbl = label.astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    picked = jnp.take_along_axis(input, safe[:, None] if input.ndim == 2
                                 else jnp.expand_dims(safe, 1), axis=1)
    loss = -jnp.squeeze(picked, axis=1)
    if weight is not None:
        w = jnp.take(weight, safe)
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(jnp.where(valid, loss, 0.0)) / \
                jnp.maximum(jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
    loss = jnp.where(valid, loss, 0.0)
    return _reduce(loss, reduction)


@defop
def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return _reduce(jnp.square(input - label), reduction)


@defop
def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return _reduce(jnp.abs(input - label), reduction)


@defop
def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    d = input - label
    loss = jnp.where(jnp.abs(d) < delta, 0.5 * d * d, delta * (jnp.abs(d) - 0.5 * delta))
    return _reduce(loss, reduction)


@defop
def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    x = jnp.clip(input, 1e-12, 1.0 - 1e-12)
    loss = -(label * jnp.log(x) + (1 - label) * jnp.log1p(-x))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@defop
def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    neg_abs = -jnp.abs(logit)
    base = jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(neg_abs))
    if pos_weight is not None:
        log_w = (pos_weight - 1) * label + 1
        base = base * log_w
    if weight is not None:
        base = base * weight
    return _reduce(base, reduction)


@defop
def kl_div(input, label, reduction="mean", name=None):  # noqa: A002
    loss = label * (jnp.log(jnp.clip(label, 1e-12, None)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@defop
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",  # noqa: A002
                        name=None):
    return _reduce(jnp.maximum(-label * (input - other) + margin, 0.0), reduction)


@defop
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    loss = jnp.where(label == 1, input, jnp.maximum(margin - input, 0.0))
    return _reduce(loss, reduction)


@defop
def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    cos = jnp.sum(input1 * input2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1), 1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
    return _reduce(loss, reduction)


@defop
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,  # noqa: A002
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def pdist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p), axis=-1),
                         1.0 / p)
    dp = pdist(input, positive)
    dn = pdist(input, negative)
    if swap:
        dn = jnp.minimum(dn, pdist(positive, negative))
    return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)


@defop
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    p = jax.nn.sigmoid(logit)
    ce = jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * jnp.power(1 - p_t, gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


@defop
def square_error_cost(input, label, name=None):  # noqa: A002
    return jnp.square(input - label)


@defop
def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    return -label * jnp.log(input + epsilon) - \
        (1 - label) * jnp.log(1 - input + epsilon)


@defop
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC via the standard forward algorithm in log space (lax.scan over time).

    log_probs: [T, B, C] (paddle convention: max_logit_length first).
    """
    if log_probs.ndim == 3 and log_probs.shape[0] != labels.shape[0]:
        lp = log_probs  # already [T, B, C]
    else:
        lp = jnp.swapaxes(log_probs, 0, 1)
    lp = jax.nn.log_softmax(lp, axis=-1)
    T, B, C = lp.shape
    L = labels.shape[1]
    S = 2 * L + 1
    NEG = jnp.array(-1e30, lp.dtype)

    ext = jnp.full((B, S), blank, dtype=labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    same = jnp.concatenate(
        [jnp.zeros((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

    alpha0 = jnp.full((B, S), NEG)
    alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
    alpha0 = alpha0.at[:, 1].set(jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0])

    def step(alpha, lp_t):
        a_shift1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        a_shift2 = jnp.where(same, NEG, a_shift2)
        merged = jnp.logaddexp(alpha, jnp.logaddexp(a_shift1, a_shift2))
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        new_alpha = merged + emit
        return new_alpha, new_alpha

    _, alphas = jax.lax.scan(step, alpha0, lp[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, S]

    t_idx = jnp.clip(input_lengths.astype(jnp.int32) - 1, 0, T - 1)
    final = alphas[t_idx, jnp.arange(B)]  # [B, S]
    s_last = 2 * label_lengths.astype(jnp.int32)
    a_end = jnp.take_along_axis(final, s_last[:, None], axis=1)[:, 0]
    a_end2 = jnp.take_along_axis(final, jnp.maximum(s_last - 1, 0)[:, None],
                                 axis=1)[:, 0]
    loss = -jnp.logaddexp(a_end, a_end2)
    return _reduce(loss, reduction)
