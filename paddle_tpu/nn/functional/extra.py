"""nn.functional long tail — parity with the reference exports that were
still absent (python/paddle/nn/functional/__init__.py): distance /
margin losses, hierarchical sigmoid, ArcFace-style margin softmax,
sparse (CSR-masked) attention, pad/unpool variants and in-place
activation forms."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.op import defop
from ...core.tensor import Tensor

__all__ = ["bilinear", "dice_loss", "npair_loss", "zeropad2d",
           "pairwise_distance", "soft_margin_loss",
           "multi_label_soft_margin_loss",
           "triplet_margin_with_distance_loss", "thresholded_relu",
           "hsigmoid_loss", "margin_cross_entropy", "sparse_attention",
           "max_unpool1d", "max_unpool3d", "elu_", "softmax_", "tanh_"]


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    if reduction == "none":
        return loss
    raise ValueError(f"reduction should be mean|sum|none, got {reduction}")


def bilinear(x1, x2, weight, bias=None, name=None):
    """common.bilinear: out[b,o] = x1[b,i] W[o,i,j] x2[b,j] (+ bias) —
    the same kernel as ops.extended.bilinear_tensor_product (one einsum
    to optimize/shard, two API names)."""
    from ...ops.extended import bilinear_tensor_product
    return bilinear_tensor_product(x1, x2, weight, bias)


@defop
def dice_loss(input, label, epsilon=1e-5, name=None):
    """loss.dice_loss: input [N, ..., C] probabilities, label [N, ..., 1]
    class ids."""
    label_oh = jax.nn.one_hot(label.squeeze(-1), input.shape[-1],
                              dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inter = jnp.sum(input * label_oh, axis=reduce_dims)
    union = jnp.sum(input, axis=reduce_dims) + jnp.sum(label_oh,
                                                       axis=reduce_dims)
    dice = (2 * inter + epsilon) / (union + epsilon)
    return jnp.mean(1 - dice)


@defop
def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """loss.npair_loss (the reference's N-pair metric loss): cross
    entropy over anchor·positiveᵀ similarities + L2 on the embeddings."""
    reg = l2_reg * (jnp.sum(anchor * anchor) / max(anchor.shape[0], 1)
                    + jnp.sum(positive * positive)
                    / max(positive.shape[0], 1)) * 0.25
    sim = anchor @ positive.T
    lab = labels.reshape(-1)
    same = (lab[:, None] == lab[None, :]).astype(sim.dtype)
    target = same / jnp.maximum(jnp.sum(same, axis=1, keepdims=True), 1)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(target * logp, axis=1))
    return ce + reg


@defop
def zeropad2d(x, padding, data_format="NCHW", name=None):
    l, r, t, b = (padding if not hasattr(padding, "tolist")
                  else padding.tolist())
    if data_format == "NCHW":
        widths = ((0, 0), (0, 0), (t, b), (l, r))
    else:
        widths = ((0, 0), (t, b), (l, r), (0, 0))
    return jnp.pad(x, widths)


@defop
def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    d = x - y + epsilon
    return jnp.linalg.norm(d.astype(jnp.promote_types(d.dtype,
                                                      jnp.float32)),
                           ord=p, axis=-1, keepdims=keepdim
                           ).astype(d.dtype)


@defop
def soft_margin_loss(input, label, reduction="mean", name=None):
    loss = jnp.log1p(jnp.exp(-label.astype(input.dtype) * input))
    return _reduce(loss, reduction)


@defop
def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    lab = label.astype(input.dtype)
    loss = -(lab * jax.nn.log_sigmoid(input)
             + (1 - lab) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        loss = loss * weight
    loss = jnp.mean(loss, axis=-1)
    return _reduce(loss, reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    dist = distance_function or pairwise_distance

    def dval(a, b):
        out = dist(a, b)
        return out._value if isinstance(out, Tensor) else jnp.asarray(out)

    dp = dval(input, positive)
    dn = dval(input, negative)
    if swap:
        dn = jnp.minimum(dn, dval(positive, negative))
    loss = jnp.maximum(dp - dn + margin, 0)
    out = _reduce(loss, reduction)
    return out if isinstance(out, Tensor) else Tensor(out, _internal=True)


@defop
def thresholded_relu(x, threshold=1.0, name=None):
    return jnp.where(x > threshold, x, 0)


def _default_tree_paths(num_classes):
    """Complete-binary-tree paths for the default hsigmoid tree: leaf of
    class c sits at heap position c + num_classes - 1 over internal
    nodes 0..num_classes-2 (the reference kernel's implicit layout)."""
    depth_max = int(np.ceil(np.log2(max(num_classes, 2))))
    table = np.full((num_classes, depth_max), -1, np.int64)
    code = np.zeros((num_classes, depth_max), np.float32)
    for c in range(num_classes):
        node = c + num_classes - 1
        path, bits = [], []
        while node > 0:
            parent = (node - 1) // 2
            path.append(parent)
            bits.append(1.0 if node == 2 * parent + 2 else 0.0)
            node = parent
        path.reverse()
        bits.reverse()
        table[c, :len(path)] = path
        code[c, :len(bits)] = bits
    return table, code


@defop
def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """loss.hsigmoid_loss (hierarchical sigmoid): sum of BCE losses
    along each label's root-to-leaf path.  Default path = complete
    binary tree over `num_classes-1` internal nodes; custom trees pass
    path_table/path_code (the reference kernel contract)."""
    if path_table is None or path_code is None:
        t, c = _default_tree_paths(int(num_classes))
        path_table, path_code = jnp.asarray(t), jnp.asarray(c)
    lab = label.reshape(-1)
    tbl = path_table[lab]                       # [N, D]
    code = path_code[lab].astype(input.dtype)   # [N, D]
    valid = (tbl >= 0)
    idx = jnp.maximum(tbl, 0)
    w = weight[idx]                             # [N, D, E]
    logits = jnp.einsum("nde,ne->nd", w, input)
    if bias is not None:
        logits = logits + bias.reshape(-1)[idx]
    # BCE with target = code: -[code*log σ(z) + (1-code)*log σ(-z)]
    loss = -(code * jax.nn.log_sigmoid(logits)
             + (1 - code) * jax.nn.log_sigmoid(-logits))
    loss = jnp.sum(jnp.where(valid, loss, 0), axis=1, keepdims=True)
    return loss


@defop
def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean",
                         name=None):
    """loss.margin_cross_entropy (ArcFace family): logits are cosines;
    the target class logit θ becomes cos(m1·θ + m2) − m3, everything
    scales by s, then softmax CE.  Single-group form (the reference's
    model-parallel group path shards classes; here GSPMD shards the
    same dense math)."""
    lab = label.reshape(-1)
    oh = jax.nn.one_hot(lab, logits.shape[-1], dtype=logits.dtype)
    # keep strictly inside (-1, 1): d(arccos)/dx blows up at the ends and
    # would poison the backward for saturated cosines
    eps = 1e-6
    cos = jnp.clip(logits, -1.0 + eps, 1.0 - eps)
    theta = jnp.arccos(cos)
    target_logit = jnp.cos(margin1 * theta + margin2) - margin3
    adjusted = jnp.where(oh > 0, target_logit, cos) * scale
    logp = jax.nn.log_softmax(adjusted, axis=-1)
    loss = -jnp.sum(oh * logp, axis=-1, keepdims=True)
    if reduction == "mean":
        loss = jnp.mean(loss)
    elif reduction == "sum":
        loss = jnp.sum(loss)
    elif reduction is not None and reduction != "none":
        raise ValueError(f"bad reduction {reduction}")
    if return_softmax:
        return loss, jnp.exp(logp)
    return loss


@defop
def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """functional.sparse_attention: attention restricted to the CSR
    sparsity pattern (offset [B,H,L+1], columns [B,H,nnz]).  The
    reference's CUDA kernel walks the CSR lists; here the pattern
    becomes a dense mask feeding XLA's fused softmax — same output,
    TPU-shaped execution."""
    b, h, L, d = query.shape
    scores = jnp.einsum("bhld,bhmd->bhlm", query, key) / np.sqrt(d)
    # scatter the CSR pattern into a dense [B,H,L,L] mask: entry j of the
    # columns list belongs to the row whose offset range contains j
    mask = jnp.zeros((b, h, L, L), bool)
    nnz = sparse_csr_columns.shape[-1]

    def row_ids(off):
        return jnp.clip(jnp.searchsorted(off, jnp.arange(nnz),
                                         side="right") - 1, 0, L - 1)

    rids = jax.vmap(jax.vmap(row_ids))(sparse_csr_offset)  # [B,H,nnz]
    bi = jnp.arange(b)[:, None, None]
    hi = jnp.arange(h)[None, :, None]
    mask = mask.at[bi, hi, rids, sparse_csr_columns].set(True)
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, scores.dtype)
    scores = jnp.where(mask, scores, neg)
    if attn_mask is not None:
        scores = scores + attn_mask
    if key_padding_mask is not None:
        scores = jnp.where(key_padding_mask[:, None, None, :] > 0,
                           scores, neg)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = jnp.where(mask, probs, 0)
    return jnp.einsum("bhlm,bhmd->bhld", probs, value)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL", name=None):
    from ...ops.extended import max_unpool2d as _u2
    x4 = x.unsqueeze(-2) if isinstance(x, Tensor) else x[..., None, :]
    i4 = indices.unsqueeze(-2) if isinstance(indices, Tensor) \
        else indices[..., None, :]
    out_sz = None if output_size is None else \
        list(output_size[:-1]) + [1, output_size[-1]]
    out = _u2(x4, i4, (1, kernel_size), (1, stride or kernel_size),
              padding, out_sz, data_format="NCHW")
    return out.squeeze(-2)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW", name=None):
    """Scatter pooled values back along D,H,W (unpool3d kernel)."""
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    iv = indices._value if isinstance(indices, Tensor) \
        else jnp.asarray(indices)
    ks = (kernel_size,) * 3 if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else ((stride,) * 3 if isinstance(stride, int)
                                    else tuple(stride))
    pd = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    n, c, dd, hh, ww = v.shape
    if output_size is None:
        od = (dd - 1) * st[0] + ks[0] - 2 * pd[0]
        oh = (hh - 1) * st[1] + ks[1] - 2 * pd[1]
        ow = (ww - 1) * st[2] + ks[2] - 2 * pd[2]
    else:
        od, oh, ow = output_size[-3:]
    flat = jnp.zeros((n, c, od * oh * ow), v.dtype)
    idx = iv.reshape(n, c, -1)
    flat = flat.at[jnp.arange(n)[:, None, None],
                   jnp.arange(c)[None, :, None], idx].set(
        v.reshape(n, c, -1))
    return Tensor(flat.reshape(n, c, od, oh, ow), _internal=True)


# -- in-place activation forms ----------------------------------------------

from ...ops.compat_surface import _inplace  # noqa: E402  (one helper,
# shared with the paddle.*_ in-place surface)


def elu_(x, alpha=1.0, name=None):
    from .activation import elu
    return _inplace(x, elu(x, alpha))


def softmax_(x, axis=-1, dtype=None, name=None):
    from .activation import softmax
    return _inplace(x, softmax(x, axis=axis, dtype=dtype))


def tanh_(x, name=None):
    from ...ops.math import tanh
    return _inplace(x, tanh(x))
