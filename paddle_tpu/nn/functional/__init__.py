"""paddle.nn.functional parity surface."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import (  # noqa: F401
    enable_flash_attention,
    fused_ln_linear,
    fused_qkv_attention,
    scaled_dot_product_attention,
)
from ...ops.manipulation import pad  # noqa: F401
from ...ops.creation import one_hot  # noqa: F401
from .extra import *  # noqa: F401,F403
# vision/sequence functionals whose kernels live in ops.extended
from ...ops.extended import (affine_grid, diag_embed,  # noqa: F401
                             gather_tree, grid_sample, max_unpool2d,
                             temporal_shift)
