"""paddle.nn.functional parity surface."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import (  # noqa: F401
    enable_flash_attention,
    fused_ln_linear,
    fused_qkv_attention,
    scaled_dot_product_attention,
)
from ...ops.manipulation import pad  # noqa: F401
from ...ops.creation import one_hot  # noqa: F401
