"""Pooling functionals via lax.reduce_window
(reference: python/paddle/nn/functional/pooling.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.op import defop


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    return v * n if len(v) == 1 else v


def _pad_spec(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding[-n:]]


def _pool(x, kernel, stride, padding, n, channel_last, kind, ceil_mode=False,
          exclusive=True):
    kernel = _tuplize(kernel, n)
    stride = _tuplize(stride if stride is not None else kernel, n)
    pad = _pad_spec(padding, n)

    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        spatial = list(range(1, 1 + n))
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        spatial = list(range(2, 2 + n))

    if isinstance(pad, str):
        lax_pad = pad
    else:
        full = [(0, 0)] * x.ndim
        for i, d in enumerate(spatial):
            lo, hi = pad[i]
            if ceil_mode:
                size = x.shape[d]
                k, s = kernel[i], stride[i]
                out_ceil = -(-(size + lo + hi - k) // s) + 1
                needed = (out_ceil - 1) * s + k - (size + lo)
                hi = max(hi, needed)
            full[d] = (lo, hi)
        lax_pad = full

    if kind == "max":
        init = jnp.array(-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                         else jnp.iinfo(x.dtype).min, dtype=x.dtype)
        return jax.lax.reduce_window(x, init, jax.lax.max, window, strides, lax_pad)

    # avg pool: sum then divide (exclusive → divide by actual window size)
    zero = jnp.zeros((), x.dtype)
    summed = jax.lax.reduce_window(x, zero, jax.lax.add, window, strides, lax_pad)
    if exclusive and (isinstance(lax_pad, str) or
                      any(p != (0, 0) for p in lax_pad)):
        counts = jax.lax.reduce_window(jnp.ones_like(x), zero, jax.lax.add,
                                       window, strides, lax_pad)
        return summed / counts
    return summed / float(np.prod(kernel))


@defop
def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 "max", ceil_mode)


@defop
def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 "max", ceil_mode)


@defop
def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 "max", ceil_mode)


@defop
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 "avg", ceil_mode, exclusive)


@defop
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    out = _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                "avg", ceil_mode, exclusive)
    if divisor_override:
        k = _tuplize(kernel_size, 2)
        out = out * (float(np.prod(k)) / divisor_override)
    return out


@defop
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 "avg", ceil_mode, exclusive)


def _adaptive_pool(x, output_size, n, channel_last, kind):
    out_sizes = _tuplize(output_size, n)
    spatial = list(range(1, 1 + n)) if channel_last else list(range(2, 2 + n))
    # adaptive pooling = per-output-bin variable windows; implement by splitting
    # each spatial dim into bins with integer boundaries (phi adaptive kernels)
    out = x
    for i, d in enumerate(spatial):
        size = out.shape[d]
        bins = out_sizes[i] if out_sizes[i] is not None else size
        # window [floor(b*size/bins), ceil((b+1)*size/bins)) — never empty,
        # also correct when bins > size (windows overlap / repeat)
        starts = [(size * b) // bins for b in range(bins)]
        ends = [-(-(size * (b + 1)) // bins) for b in range(bins)]
        if size % bins == 0:
            # uniform bins → reshape-reduce (fast path)
            k = size // bins
            new_shape = out.shape[:d] + (bins, k) + out.shape[d + 1:]
            r = out.reshape(new_shape)
            out = jnp.max(r, axis=d + 1) if kind == "max" else jnp.mean(r, axis=d + 1)
        else:
            chunks = []
            for b in range(bins):
                sl = [slice(None)] * out.ndim
                sl[d] = slice(starts[b], ends[b])
                piece = out[tuple(sl)]
                red = jnp.max(piece, axis=d, keepdims=True) if kind == "max" \
                    else jnp.mean(piece, axis=d, keepdims=True)
                chunks.append(red)
            out = jnp.concatenate(chunks, axis=d)
    return out


@defop
def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, False, "avg")


@defop
def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format == "NHWC", "avg")


@defop
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format == "NDHWC", "avg")


@defop
def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, False, "max")


@defop
def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, False, "max")


@defop
def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, False, "max")
