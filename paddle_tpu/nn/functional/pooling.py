"""Pooling functionals via lax.reduce_window
(reference: python/paddle/nn/functional/pooling.py)."""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ...core.op import defop


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _rw_max_pool(x, window, strides, pads):
    """Max pool as reduce_window with an explicit select-and-scatter
    backward.  The generic reduce_window JVP fails partial-eval when nested
    inside the eager tape's per-op jax.vjp (docs/PERF.md); this custom rule
    sidesteps it AND avoids the patches form, which materializes a
    kernel-size× copy of the activation (measured 9 ms/step of ResNet-50's
    38 ms, tools/profile_model.py)."""
    neg = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
           else jnp.iinfo(x.dtype).min)
    return jax.lax.reduce_window(x, jnp.asarray(neg, x.dtype), jax.lax.max,
                                 window, strides, pads)


def _rw_max_pool_fwd(x, window, strides, pads):
    return _rw_max_pool(x, window, strides, pads), x


def _rw_max_pool_bwd(window, strides, pads, x, g):
    from jax._src.lax import lax as lax_internal
    from jax._src.lax.windowed_reductions import select_and_scatter_add_p
    dx = select_and_scatter_add_p.bind(
        g, x, select_prim=lax_internal.ge_p,
        window_dimensions=tuple(window), window_strides=tuple(strides),
        padding=tuple(pads))
    return (dx,)


_rw_max_pool.defvjp(_rw_max_pool_fwd, _rw_max_pool_bwd)


def _tuplize(v, n):
    if isinstance(v, int) or v is None:
        return (v,) * n
    v = tuple(None if x is None else int(x) for x in v)
    return v * n if len(v) == 1 else v


def _pad_spec(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding[-n:]]


def _max_pool_patches(x, kernel, stride, lax_pad, n, channel_last, spatial,
                      with_index=False):
    """Max pooling as window-patch extraction + reduce-max.  Channel-first
    internally; returns (out, flat_spatial_indices) when with_index (the
    reference max_pool*d return_mask contract: indices into the flattened
    UNPADDED input spatial volume)."""
    # pad with a LARGE finite negative, not -inf and not f32-min: patch
    # extraction is a one-hot convolution, -inf * 0 = NaN, and f32-min
    # overflows to -inf under the TPU's default bf16 conv passes
    neg = (jnp.asarray(-1e30, x.dtype)
           if jnp.issubdtype(x.dtype, jnp.floating)
           else jnp.iinfo(x.dtype).min)
    if channel_last:
        perm = (0, x.ndim - 1) + tuple(range(1, x.ndim - 1))
        x = jnp.transpose(x, perm)
    orig_spatial = x.shape[2:]
    if isinstance(lax_pad, str):
        if lax_pad.upper() == "SAME":
            # materialize SAME pads explicitly: the patch conv would pad
            # with 0 (wrong identity for max) and the mask indices need
            # the true low pads
            sp = []
            for i in range(n):
                size = orig_spatial[i]
                out = -(-size // stride[i])
                total = max(0, (out - 1) * stride[i] + kernel[i] - size)
                sp.append((total // 2, total - total // 2))
            lax_pad = None  # handled below
        else:
            sp = [(0, 0)] * n
    else:
        sp = [lax_pad[d] for d in spatial]
    pads = "VALID"
    pad_lo = [p[0] for p in sp]
    if any(p != (0, 0) for p in sp):
        x = jnp.pad(x, [(0, 0), (0, 0)] + list(sp), constant_values=neg)
    c = x.shape[1]
    # HIGHEST precision: the one-hot conv must not round the values
    # through bf16 passes
    patches = jax.lax.conv_general_dilated_patches(
        x, kernel, stride, pads, precision=jax.lax.Precision.HIGHEST)
    ksz = int(np.prod(kernel))
    out_spatial = patches.shape[2:]
    # feature dim ordering: [C, *kernel] (C slowest)
    patches = patches.reshape((patches.shape[0], c, ksz) + out_spatial)
    out = jnp.max(patches, axis=2)

    def to_layout(t):
        if channel_last:
            return jnp.transpose(t, (0,) + tuple(range(2, t.ndim)) + (1,))
        return t

    if not with_index:
        return to_layout(out)
    widx = jnp.argmax(patches, axis=2)  # row-major index within the window
    offs = []
    rem = widx
    for k in reversed(kernel):
        offs.append(rem % k)
        rem = rem // k
    offs = offs[::-1]
    flat = None
    for i in range(n):
        grid = jnp.arange(out_spatial[i]) * stride[i]
        shape = [1] * widx.ndim
        shape[2 + i] = out_spatial[i]
        coord = grid.reshape(shape) + offs[i] - pad_lo[i]
        coord = jnp.clip(coord, 0, orig_spatial[i] - 1)
        flat = coord if flat is None else flat * orig_spatial[i] + coord
    return to_layout(out), to_layout(flat.astype(jnp.int64))


def _pool(x, kernel, stride, padding, n, channel_last, kind, ceil_mode=False,
          exclusive=True, return_mask=False):
    kernel = _tuplize(kernel, n)
    stride = _tuplize(stride if stride is not None else kernel, n)
    pad = _pad_spec(padding, n)

    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        spatial = list(range(1, 1 + n))
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        spatial = list(range(2, 2 + n))

    if isinstance(pad, str):
        lax_pad = pad
    else:
        full = [(0, 0)] * x.ndim
        for i, d in enumerate(spatial):
            lo, hi = pad[i]
            if ceil_mode:
                size = x.shape[d]
                k, s = kernel[i], stride[i]
                out_ceil = -(-(size + lo + hi - k) // s) + 1
                needed = (out_ceil - 1) * s + k - (size + lo)
                hi = max(hi, needed)
            full[d] = (lo, hi)
        lax_pad = full

    if kind == "max":
        if return_mask:
            if jnp.issubdtype(x.dtype, jnp.integer):
                raise NotImplementedError(
                    "max_pool with return_mask=True is not supported for "
                    "integer dtypes: the window-argmax path is a one-hot "
                    "convolution, which does not lower for integers on "
                    "TPU; cast to a float dtype or drop return_mask")
            # the patch form is the only one that yields window argmax
            # indices; it materializes kernel-size× the activation, so it
            # is reserved for the mask case
            return _max_pool_patches(x, kernel, stride, lax_pad, n,
                                     channel_last, spatial, with_index=True)
        if isinstance(lax_pad, str):
            pads = jax.lax.padtype_to_pads(x.shape, window, strides, lax_pad)
        else:
            pads = lax_pad
        return _rw_max_pool(x, tuple(window), tuple(strides),
                            tuple(tuple(p) for p in pads))

    # avg pool: sum then divide (exclusive → divide by actual window size)
    zero = jnp.zeros((), x.dtype)
    summed = jax.lax.reduce_window(x, zero, jax.lax.add, window, strides, lax_pad)
    if exclusive and (isinstance(lax_pad, str) or
                      any(p != (0, 0) for p in lax_pad)):
        counts = jax.lax.reduce_window(jnp.ones_like(x), zero, jax.lax.add,
                                       window, strides, lax_pad)
        return summed / counts
    return summed / float(np.prod(kernel))


@defop
def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 "max", ceil_mode, return_mask=return_mask)


@defop
def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 "max", ceil_mode, return_mask=return_mask)


@defop
def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 "max", ceil_mode, return_mask=return_mask)


@defop
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 "avg", ceil_mode, exclusive)


@defop
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    out = _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                "avg", ceil_mode, exclusive)
    if divisor_override:
        k = _tuplize(kernel_size, 2)
        out = out * (float(np.prod(k)) / divisor_override)
    return out


@defop
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 "avg", ceil_mode, exclusive)


def _adaptive_pool(x, output_size, n, channel_last, kind):
    out_sizes = _tuplize(output_size, n)
    spatial = list(range(1, 1 + n)) if channel_last else list(range(2, 2 + n))
    # adaptive pooling = per-output-bin variable windows; implement by splitting
    # each spatial dim into bins with integer boundaries (phi adaptive kernels)
    out = x
    for i, d in enumerate(spatial):
        size = out.shape[d]
        bins = out_sizes[i] if out_sizes[i] is not None else size
        # window [floor(b*size/bins), ceil((b+1)*size/bins)) — never empty,
        # also correct when bins > size (windows overlap / repeat)
        starts = [(size * b) // bins for b in range(bins)]
        ends = [-(-(size * (b + 1)) // bins) for b in range(bins)]
        if size % bins == 0:
            # uniform bins → reshape-reduce (fast path)
            k = size // bins
            new_shape = out.shape[:d] + (bins, k) + out.shape[d + 1:]
            r = out.reshape(new_shape)
            out = jnp.max(r, axis=d + 1) if kind == "max" else jnp.mean(r, axis=d + 1)
        else:
            chunks = []
            for b in range(bins):
                sl = [slice(None)] * out.ndim
                sl[d] = slice(starts[b], ends[b])
                piece = out[tuple(sl)]
                red = jnp.max(piece, axis=d, keepdims=True) if kind == "max" \
                    else jnp.mean(piece, axis=d, keepdims=True)
                chunks.append(red)
            out = jnp.concatenate(chunks, axis=d)
    return out


@defop
def adaptive_avg_pool1d(x, output_size, data_format="NCL", name=None):
    return _adaptive_pool(x, output_size, 1, data_format == "NLC", "avg")


@defop
def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format == "NHWC", "avg")


@defop
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format == "NDHWC", "avg")


@defop
def adaptive_max_pool1d(x, output_size, return_mask=False,
                        data_format="NCL", name=None):
    return _adaptive_pool(x, output_size, 1, data_format == "NLC", "max")


@defop
def adaptive_max_pool2d(x, output_size, return_mask=False,
                        data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format == "NHWC", "max")


@defop
def adaptive_max_pool3d(x, output_size, return_mask=False,
                        data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format == "NDHWC", "max")
