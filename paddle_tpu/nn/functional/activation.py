"""Activation functionals (reference: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op import defop, apply_op


@defop
def relu(x, name=None):
    return jnp.maximum(x, 0)


@defop
def relu6(x, name=None):
    return jnp.clip(x, 0, 6)


@defop
def relu_(x, name=None):
    return jnp.maximum(x, 0)


@defop
def elu(x, alpha=1.0, name=None):
    return jax.nn.elu(x, alpha)


@defop
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@defop
def celu(x, alpha=1.0, name=None):
    return jax.nn.celu(x, alpha)


@defop
def gelu(x, approximate=False, name=None):
    return jax.nn.gelu(x, approximate=bool(approximate))


@defop
def sigmoid(x, name=None):
    return jax.nn.sigmoid(x)


@defop
def log_sigmoid(x, name=None):
    return jax.nn.log_sigmoid(x)


@defop
def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@defop
def hardswish(x, name=None):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


@defop
def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return jnp.clip(x, min, max)


@defop
def hardshrink(x, threshold=0.5, name=None):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@defop
def softshrink(x, threshold=0.5, name=None):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


@defop
def tanhshrink(x, name=None):
    return x - jnp.tanh(x)


@defop
def leaky_relu(x, negative_slope=0.01, name=None):
    return jnp.where(x >= 0, x, negative_slope * x)


@defop
def prelu(x, weight, data_format="NCHW", name=None):
    w = weight
    if w.ndim == 1 and w.shape[0] > 1 and x.ndim > 1:
        ch_axis = 1 if data_format[1] == "C" else x.ndim - 1
        shape = [1] * x.ndim
        shape[ch_axis] = w.shape[0]
        w = w.reshape(shape)
    return jnp.where(x >= 0, x, w * x)


@defop
def rrelu(x, lower=0.125, upper=0.3333333333333333, training=False, name=None):
    if training:
        from ...core import random as rnd
        slope = jax.random.uniform(rnd.next_key(), x.shape, x.dtype, lower, upper)
    else:
        slope = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, slope * x)


@defop
def softplus(x, beta=1.0, threshold=20.0, name=None):
    return jnp.where(x * beta > threshold, x,
                     (1.0 / beta) * jnp.log1p(jnp.exp(beta * x)))


@defop
def softsign(x, name=None):
    return x / (1.0 + jnp.abs(x))


@defop
def silu(x, name=None):
    return jax.nn.silu(x)


@defop
def swish(x, name=None):
    return jax.nn.silu(x)


@defop
def mish(x, name=None):
    return x * jnp.tanh(jax.nn.softplus(x))


@defop
def tanh(x, name=None):
    return jnp.tanh(x)


@defop
def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        from ...core.dtype import to_jax
        x = x.astype(to_jax(dtype))
    return jax.nn.softmax(x, axis=int(axis))


@defop
def log_softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        from ...core.dtype import to_jax
        x = x.astype(to_jax(dtype))
    return jax.nn.log_softmax(x, axis=int(axis))


@defop
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random as rnd
    g = -jnp.log(-jnp.log(
        jax.random.uniform(rnd.next_key(), x.shape, x.dtype, 1e-20, 1.0)))
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        onehot = jnp.zeros_like(y)
        onehot = jnp.put_along_axis(onehot, idx, 1.0, axis=axis, inplace=False)
        y = onehot + y - jax.lax.stop_gradient(y)
    return y


@defop
def maxout(x, groups, axis=1, name=None):
    axis = int(axis) % x.ndim
    c = x.shape[axis]
    new_shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


@defop
def glu(x, axis=-1, name=None):
    a, b = jnp.split(x, 2, axis=int(axis))
    return a * jax.nn.sigmoid(b)


@defop
def temperature_scaled_softmax(x, temperature=1.0, axis=-1, name=None):
    return jax.nn.softmax(x / temperature, axis=axis)
