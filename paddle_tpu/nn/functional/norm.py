"""Normalisation functionals (reference: python/paddle/nn/functional/norm.py →
phi batch_norm/layer_norm kernels).  XLA fuses these into surrounding matmuls;
a Pallas fused layernorm lives in paddle_tpu.kernels for the hot transformer
path."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op import defop, apply_op
from ...core.tensor import Tensor


@defop
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(tuple(normalized_shape))
    axes = tuple(range(x.ndim - n_axes, x.ndim))
    from ...kernels.layer_norm import layer_norm_fused, layer_norm_fused_ok
    if layer_norm_fused_ok(x, axes, weight, bias):
        # fused Pallas path: one pass per row block incl. the backward's
        # dgamma/dbeta accumulation (reference layer_norm_kernel.cu analog)
        return layer_norm_fused(x, weight, bias, epsilon)
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    """Returns normalized output; updates running stats in-place when training
    (matching the reference's in-place mean/variance update)."""
    channel_axis = 1 if data_format.startswith("NC") or x.ndim <= 2 else x.ndim - 1
    if x.ndim <= 2:
        channel_axis = x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != channel_axis)

    use_batch_stats = training and not use_global_stats

    def impl(xv, w, b, rm, rv):
        shape = [1] * xv.ndim
        shape[channel_axis] = xv.shape[channel_axis]
        half = jnp.issubdtype(xv.dtype, jnp.floating) and \
            jnp.finfo(xv.dtype).bits < 32
        if use_batch_stats:
            if half:
                # one-pass stats for half dtypes: E[x²]−E[x]² in f32 lets
                # XLA fuse both channel reductions into a single read of
                # the activation, where the two-pass mean→var form forces
                # a second dependent pass (measured on ResNet-50,
                # tools/profile_model.py).  The f32 accumulation is as
                # accurate as half-precision data allows: cancellation
                # only bites when |mean|/std exceeds what the input's own
                # mantissa can represent.
                xf = xv.astype(jnp.float32)
                mean = jnp.mean(xf, axis=reduce_axes)
                var = jnp.maximum(
                    jnp.mean(jnp.square(xf), axis=reduce_axes)
                    - jnp.square(mean), 0)
            else:
                # full-precision inputs keep the exact two-pass form in
                # their own dtype (E[x²]−E[x]² cancels catastrophically
                # for |mean| >> std even in f32)
                mean = jnp.mean(xv, axis=reduce_axes)
                var = jnp.var(xv, axis=reduce_axes)
        else:
            mean, var = rm, rv
        # fold the normalisation into one scale+shift over x: out =
        # x*scale + shift with per-channel scalars, a single fused pass
        stat_dtype = mean.dtype
        inv = jax.lax.rsqrt(var.astype(stat_dtype) + epsilon)
        scale = inv if w is None else inv * w.astype(stat_dtype)
        shift = -mean * scale
        if b is not None:
            shift = shift + b.astype(stat_dtype)
        out = xv * scale.reshape(shape).astype(xv.dtype) \
            + shift.reshape(shape).astype(xv.dtype)
        return out, mean, var

    out, mean, var = apply_op(impl, "batch_norm",
                              (x, weight, bias, running_mean, running_var), {})
    if use_batch_stats and running_mean is not None:
        with_no_grad_update(running_mean, running_var, mean, var, momentum)
    return out


def with_no_grad_update(running_mean, running_var, mean, var, momentum):
    from ...core.autograd import no_grad
    with no_grad():
        running_mean._replace_(
            (momentum * running_mean._value +
             (1 - momentum) * mean._value.astype(running_mean._value.dtype)), None)
        running_var._replace_(
            (momentum * running_var._value +
             (1 - momentum) * var._value.astype(running_var._value.dtype)), None)


@defop
def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    if data_format == "NCHW" or x.ndim <= 2:
        n, c = x.shape[0], x.shape[1]
        rest = x.shape[2:]
        g = x.reshape((n, num_groups, c // num_groups) + rest)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape)
        shape = (1, c) + (1,) * len(rest)
    else:
        n, c = x.shape[0], x.shape[-1]
        rest = x.shape[1:-1]
        g = x.reshape((n,) + rest + (num_groups, c // num_groups))
        axes = tuple(range(1, g.ndim - 2)) + (g.ndim - 1,)
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape)
        shape = (1,) * (1 + len(rest)) + (c,)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@defop
def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    axes = tuple(range(2, x.ndim)) if data_format.startswith("NC") \
        else tuple(range(1, x.ndim - 1))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    ch = 1 if data_format.startswith("NC") else x.ndim - 1
    shape[ch] = x.shape[ch]
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@defop
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    ch = 1 if data_format.startswith("NC") else x.ndim - 1
    sq = jnp.square(x)
    half = size // 2
    pad_width = [(0, 0)] * x.ndim
    pad_width[ch] = (half, size - half - 1)
    padded = jnp.pad(sq, pad_width)
    window = [1] * x.ndim
    window[ch] = size
    summed = jax.lax.reduce_window(padded, jnp.zeros((), x.dtype), jax.lax.add,
                                   tuple(window), (1,) * x.ndim, "VALID")
    return x / jnp.power(k + alpha * summed, beta)


@defop
def spectral_norm(weight, u, v, dim=0, power_iters=1, eps=1e-12, name=None):
    w = jnp.moveaxis(weight, dim, 0).reshape(weight.shape[dim], -1)
    for _ in range(power_iters):
        v = w.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = w @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ w @ v
    return weight / sigma
