"""Weight initializers (reference: python/paddle/fluid/initializer.py /
python/paddle/nn/initializer/).  Initializers are callables
``(shape, jnp_dtype) -> jnp array`` drawing from the global framework RNG."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import random as rnd


def _fan_in_out(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # Linear weights are [in, out] in paddle
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return (self.mean + self.std *
                jax.random.normal(rnd.next_key(), shape)).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return (self.mean + self.std *
                jax.random.truncated_normal(rnd.next_key(), -2.0, 2.0, shape)
                ).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.uniform(rnd.next_key(), shape, jnp.float32,
                                  self.low, self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fin, fout = _fan_in_out(shape)
        fin = self.fan_in or fin
        fout = self.fan_out or fout
        std = self.gain * math.sqrt(2.0 / (fin + fout))
        return (std * jax.random.normal(rnd.next_key(), shape)).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fin, fout = _fan_in_out(shape)
        fin = self.fan_in or fin
        fout = self.fan_out or fout
        limit = self.gain * math.sqrt(6.0 / (fin + fout))
        return jax.random.uniform(rnd.next_key(), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fin, _ = _fan_in_out(shape)
        fin = self.fan_in or fin
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / math.sqrt(fin)
        return (std * jax.random.normal(rnd.next_key(), shape)).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fin, _ = _fan_in_out(shape)
        fin = self.fan_in or fin
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fin)
        return jax.random.uniform(rnd.next_key(), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        from ..core.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = jnp.asarray(np.asarray(v), dtype=dtype)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        return (self.gain *
                jax.nn.initializers.orthogonal()(rnd.next_key(), shape,
                                                 jnp.float32)).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        centre = tuple(s // 2 for s in shape[2:])
        for i in range(min(oc, ic * self.groups)):
            out[(i, i % ic) + centre] = 1.0
        return jnp.asarray(out, dtype=dtype)


# paddle.nn.initializer re-export names
constant = Constant
normal = Normal
uniform = Uniform
