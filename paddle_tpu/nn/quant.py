"""paddle.nn.quant — quantization layer namespace (reference
nn/quant/quant_layers.py FakeQuant*/QuantizedLinear wrappers).  The
working QAT/PTQ machinery lives in paddle_tpu.quantization; this module
re-exports its layer-facing surface under the reference path."""
from ..quantization import *  # noqa: F401,F403
