"""paddle.optimizer parity surface."""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adam, AdamW, Adagrad, RMSProp, Adadelta, Adamax,
    Lamb, LarsMomentum, L1Decay, L2Decay,
)
