"""Optimizers (reference: python/paddle/optimizer/optimizer.py + phi optimizer
kernels sgd/momentum/adam/adamw/lamb).

Each optimizer defines a pure functional core:
    init_slots(param_value)                  -> dict[str, array]
    update(p, g, slots, lr, t, ctx)          -> (new_p, new_slots)
Eager ``step()`` applies it per-parameter; the jitted train step
(paddle_tpu.hapi / parallel trainers) applies the same core inside one XLA
program so param updates fuse with the backward pass.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..nn.layer_base import Layer, Parameter
from .lr import LRScheduler


class L2Decay:
    """paddle.regularizer.L2Decay."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


def _has_decay(ctx) -> bool:
    """Truthiness of the decay coefficient that also accepts the fused flat
    path's per-element coefficient VECTOR (spmd.py flat master store), where
    plain `if coeff:` would raise on a traced array."""
    c = ctx.get("decay")
    if c is None or isinstance(c, (int, float)):
        return bool(c)
    return True


class Optimizer:
    # True for optimizers whose update is purely element-wise (broadcasts
    # over any shape with vector lr/decay) — the contract the fused flat
    # parameter store needs.  Per-TENSOR-norm optimizers (Lamb, LARS) must
    # leave this False: their trust ratios would silently collapse to one
    # global norm on a flat buffer.
    _elementwise_update = False

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        self._parameters = self._collect(parameters)
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._slots: dict[int, dict[str, jnp.ndarray]] = {}
        self._step_count = 0
        self.helper = None

    @staticmethod
    def _collect(parameters):
        if parameters is None:
            return []
        if isinstance(parameters, Layer):
            return parameters.parameters()
        params = []
        for item in parameters:
            if isinstance(item, dict):
                params.extend(item["params"])
            else:
                params.append(item)
        return params

    # -- lr -----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value: float):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    def set_lr_scheduler(self, scheduler: LRScheduler):
        self._lr = scheduler

    # -- functional core (override per optimizer) ---------------------------
    def init_slots(self, p_value) -> dict:
        return {}

    def update(self, p, g, slots, lr, t, ctx) -> tuple:
        raise NotImplementedError

    def _decay_coeff(self, param) -> float:
        wd = self._weight_decay
        reg = getattr(param, "regularizer", None) if param is not None else None
        if reg is not None:
            wd = reg
        if wd is None:
            return 0.0
        if isinstance(wd, (int, float)):
            return float(wd)
        if isinstance(wd, (L2Decay,)):
            return wd.coeff
        return 0.0

    # -- eager step ---------------------------------------------------------
    @no_grad()
    def step(self):
        from ..core.selected_rows import SelectedRows
        self._step_count += 1
        lr = self.get_lr()
        params_grads = [(p, p.grad) for p in self._parameters
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            # selected-rows grads densify for global clipping (the
            # reference merges selected_rows in ClipGradByGlobalNorm too)
            params_grads = [(p, Tensor(g.to_dense(), stop_gradient=True)
                             if isinstance(g, SelectedRows) else g)
                            for p, g in params_grads]
            params_grads = self._grad_clip(params_grads)
        t = self._step_count
        for p, g in params_grads:
            slots = self._slots.get(id(p))
            if slots is None:
                slots = self.init_slots(p._value)
                self._slots[id(p)] = slots
            plr = lr * p.optimize_attr.get("learning_rate", 1.0) \
                if isinstance(p, Parameter) else lr
            ctx = {"decay": self._decay_coeff(p)}
            if isinstance(g, SelectedRows):
                new_p, new_slots = self.update_sparse(
                    p._value, g.merged(), slots, plr, t, ctx)
            else:
                new_p, new_slots = self.update(
                    p._value, g._value.astype(p._value.dtype), slots, plr,
                    t, ctx)
            p._replace_(new_p, None)
            self._slots[id(p)] = new_slots

    def update_sparse(self, p, g, slots, lr, t, ctx):
        """Row-wise update for SelectedRows grads.  Default: LAZY mode
        (the reference's sparse adam `lazy_mode`, adam_op.h:470): gather
        the touched rows of param+slots, run the dense rule on that slice,
        scatter back — untouched rows see no decay and no moment decay."""
        rows = g.rows
        sub_p = p[rows]
        sub_slots = {k: (v[rows] if getattr(v, "ndim", 0) and
                         v.shape[:1] == p.shape[:1] else v)
                     for k, v in slots.items()}
        new_sub, new_sub_slots = self.update(
            sub_p, g.values.astype(p.dtype), sub_slots, lr, t, ctx)
        new_p = p.at[rows].set(new_sub.astype(p.dtype))
        new_slots = {}
        for k, v in slots.items():
            nv = new_sub_slots[k]
            if getattr(v, "ndim", 0) and v.shape[:1] == p.shape[:1]:
                new_slots[k] = v.at[rows].set(nv)
            else:
                new_slots[k] = nv
        return new_p, new_slots

    def clear_grad(self, set_to_zero=True):
        for p in self._parameters:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static.graph import Variable as _GraphVar
        if isinstance(loss, _GraphVar):
            # static-graph mode (reference: append backward + opt ops to
            # the Program): record the train op; Executor.run evaluates
            # the loss eagerly, backprops and steps over the program's
            # persistable parameters
            from .. import static as _static
            prog = loss.program or _static.default_main_program()
            prog._train_op = (loss, self)
            return None, None
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    # -- state --------------------------------------------------------------
    def state_dict(self) -> dict:
        sd = {"LR_Scheduler": (self._lr.state_dict()
                               if isinstance(self._lr, LRScheduler) else {}),
              "master_weights": {}, "step_count": self._step_count}
        for i, p in enumerate(self._parameters):
            slots = self._slots.get(id(p))
            if slots:
                for k, v in slots.items():
                    sd[f"{p.name}_{k}"] = Tensor(v, _internal=True)
        return sd

    def set_state_dict(self, state_dict):
        sc = state_dict.get("step_count", 0)
        self._step_count = int(sc.numpy()) if hasattr(sc, "numpy") else int(sc)
        if isinstance(self._lr, LRScheduler) and state_dict.get("LR_Scheduler"):
            self._lr.set_state_dict(state_dict["LR_Scheduler"])
        if not self._parameters:
            return
        # group slot entries by parameter-name prefix (insertion order ==
        # the order state_dict() wrote them, i.e. parameter order)
        special = {"LR_Scheduler", "master_weights", "step_count"}
        probe = jnp.zeros((1,), self._parameters[0]._value.dtype)
        slot_names = set(self.init_slots(probe))
        by_prefix: dict = {}
        for key, v in state_dict.items():
            if key in special:
                continue
            for sn in slot_names:
                if key.endswith(f"_{sn}"):
                    prefix = key[: -len(sn) - 1]
                    by_prefix.setdefault(prefix, {})[sn] = v
                    break
        prefixes = list(by_prefix)
        # matching policy: EITHER all-by-name OR all-by-position — mixing
        # the two can pair shifted auto-generated names with the wrong
        # parameter's slots (silent same-shape corruption)
        if all(p.name in by_prefix for p in self._parameters):
            src_of = {id(p): by_prefix[p.name] for p in self._parameters}
        elif len(prefixes) == len(self._parameters):
            src_of = {id(p): by_prefix[prefixes[i]]
                      for i, p in enumerate(self._parameters)}
        else:
            import warnings
            warnings.warn(
                "optimizer state restore: checkpoint slot names don't match "
                "this optimizer's parameters and counts differ "
                f"({len(prefixes)} vs {len(self._parameters)}); slots not "
                "restored")
            src_of = {}
        for p in self._parameters:
            src = src_of.get(id(p))
            if not src:
                continue
            slots = self.init_slots(p._value)
            for k in list(slots):
                if k in src:
                    v = src[k]
                    slots[k] = v._value if isinstance(v, Tensor) \
                        else jnp.asarray(v)
            self._slots[id(p)] = slots

    def _parameter_list(self):
        return self._parameters


class SGD(Optimizer):
    _elementwise_update = True
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def update(self, p, g, slots, lr, t, ctx):
        if _has_decay(ctx):
            g = g + ctx["decay"] * p
        return p - lr * g, slots


class Momentum(Optimizer):
    _elementwise_update = True
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_slots(self, p_value):
        return {"velocity": jnp.zeros_like(p_value)}

    def update(self, p, g, slots, lr, t, ctx):
        if _has_decay(ctx):
            g = g + ctx["decay"] * p
        v = self._momentum * slots["velocity"] + g
        if self._nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    _elementwise_update = True
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon

    def init_slots(self, p_value):
        return {"moment1": jnp.zeros_like(p_value),
                "moment2": jnp.zeros_like(p_value)}

    def update(self, p, g, slots, lr, t, ctx):
        if _has_decay(ctx):
            g = g + ctx["decay"] * p  # L2 reg folded into grad (Adam, not AdamW)
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + self._eps)
        return new_p, {"moment1": m, "moment2": v}


class AdamW(Adam):
    _elementwise_update = True
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None,
                 multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip)
        self._wd = float(weight_decay) if not isinstance(weight_decay, (L1Decay, L2Decay)) \
            else weight_decay.coeff
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decay_coeff(self, param):
        if self._apply_decay_param_fun is not None and param is not None \
                and not self._apply_decay_param_fun(param.name):
            return 0.0
        return self._wd

    def update(self, p, g, slots, lr, t, ctx):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        # decoupled weight decay (reference adamw kernel: p *= (1 - lr*coeff))
        p = p * (1.0 - lr * ctx["decay"])
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + self._eps)
        return new_p, {"moment1": m, "moment2": v}


class Adagrad(Optimizer):
    _elementwise_update = True
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def init_slots(self, p_value):
        return {"moment": jnp.full_like(p_value, self._init_acc)}

    def update(self, p, g, slots, lr, t, ctx):
        if _has_decay(ctx):
            g = g + ctx["decay"] * p
        acc = slots["moment"] + jnp.square(g)
        return p - lr * g / (jnp.sqrt(acc) + self._eps), {"moment": acc}


class RMSProp(Optimizer):
    _elementwise_update = True
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho = rho
        self._eps = epsilon
        self._momentum = momentum
        self._centered = centered

    def init_slots(self, p_value):
        return {"mean_square": jnp.zeros_like(p_value),
                "mean_grad": jnp.zeros_like(p_value),
                "velocity": jnp.zeros_like(p_value)}

    def update(self, p, g, slots, lr, t, ctx):
        if _has_decay(ctx):
            g = g + ctx["decay"] * p
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * jnp.square(g)
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
        else:
            mg = slots["mean_grad"]
            denom = jnp.sqrt(ms + self._eps)
        v = self._momentum * slots["velocity"] + lr * g / denom
        return p - v, {"mean_square": ms, "mean_grad": mg, "velocity": v}


class Adadelta(Optimizer):
    _elementwise_update = True
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps = epsilon
        self._rho = rho

    def init_slots(self, p_value):
        return {"avg_squared_grad": jnp.zeros_like(p_value),
                "avg_squared_update": jnp.zeros_like(p_value)}

    def update(self, p, g, slots, lr, t, ctx):
        if _has_decay(ctx):
            g = g + ctx["decay"] * p
        asg = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * jnp.square(g)
        upd = g * jnp.sqrt(slots["avg_squared_update"] + self._eps) / \
            jnp.sqrt(asg + self._eps)
        asu = self._rho * slots["avg_squared_update"] + (1 - self._rho) * jnp.square(upd)
        return p - lr * upd, {"avg_squared_grad": asg, "avg_squared_update": asu}


class Adamax(Optimizer):
    _elementwise_update = True
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_slots(self, p_value):
        return {"moment": jnp.zeros_like(p_value),
                "inf_norm": jnp.zeros_like(p_value)}

    def update(self, p, g, slots, lr, t, ctx):
        if _has_decay(ctx):
            g = g + ctx["decay"] * p
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(g))
        new_p = p - lr / (1 - self._beta1 ** t) * m / (u + self._eps)
        return new_p, {"moment": m, "inf_norm": u}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_slots(self, p_value):
        return {"moment1": jnp.zeros_like(p_value),
                "moment2": jnp.zeros_like(p_value)}

    def _decay_coeff(self, param):
        if self._exclude_fn is not None and param is not None \
                and self._exclude_fn(param):
            return 0.0
        return self._wd

    def update(self, p, g, slots, lr, t, ctx):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._eps) + ctx["decay"] * p
        w_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {"moment1": m, "moment2": v}


class LarsMomentum(Momentum):
    """LARS (reference: lars_momentum op)."""

    # per-TENSOR trust ratio (norm(p)/norm(g)): flat packing would collapse
    # it to one global norm — opt out of the inherited Momentum flag
    _elementwise_update = False

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0, name=None):
        super().__init__(learning_rate, momentum, parameters, False, None,
                         grad_clip)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon

    def update(self, p, g, slots, lr, t, ctx):
        w_norm = jnp.linalg.norm(p)
        g_norm = jnp.linalg.norm(g)
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm /
            (g_norm + self._lars_wd * w_norm + self._eps), 1.0)
        v = self._momentum * slots["velocity"] + \
            lr * local_lr * (g + self._lars_wd * p)
        return p - v, {"velocity": v}
