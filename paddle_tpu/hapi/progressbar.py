"""Minimal progress bar (reference: hapi/progressbar.py)."""
from __future__ import annotations

import sys
import time


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, start=True,
                 file=sys.stdout):
        self._num = num
        self._width = width
        self._verbose = verbose
        self._file = file
        self._start = time.time()
        self._last_update = 0

    def update(self, current_num, values=None):
        if self._verbose == 0:
            return
        now = time.time()
        msg = f"step {current_num}"
        if self._num:
            msg += f"/{self._num}"
        for k, v in (values or []):
            if isinstance(v, float):
                msg += f" - {k}: {v:.4f}"
            else:
                msg += f" - {k}: {v}"
        elapsed = now - self._start
        msg += f" - {elapsed:.0f}s"
        end = "\n" if (self._num and current_num >= self._num) or \
            self._verbose == 2 else "\r"
        self._file.write(msg + end)
        self._file.flush()
        self._last_update = now
