"""hapi — the Keras-like high-level API (reference: python/paddle/hapi/,
`Model` at hapi/model.py:915, callbacks at hapi/callbacks.py)."""
from . import callbacks  # noqa: F401
from .model import Model  # noqa: F401
from .summary import summary  # noqa: F401
