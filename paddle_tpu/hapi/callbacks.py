"""hapi callbacks — parity with python/paddle/hapi/callbacks.py
(ProgBarLogger, ModelCheckpoint:534, LRScheduler:599, EarlyStopping:690,
VisualDL:844, ReduceLROnPlateau:960)."""
from __future__ import annotations

import numbers
import os
import warnings

import numpy as np

from .progressbar import ProgressBar


def _scalar(v):
    """First scalar of a logs value.  Plain numbers pass through; lists,
    arrays and deferred DeviceLossList losses (anything array-convertible)
    fetch here — the ONE place the dispatch-ahead loss path syncs, so a
    callback that never reads a loss never forces it to host."""
    if isinstance(v, numbers.Number):
        return v
    return float(np.ravel(np.asarray(v))[0])


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = callbacks if callbacks is not None else []
    cbks = cbks if isinstance(cbks, (list, tuple)) else [cbks]
    if not any(isinstance(k, ProgBarLogger) for k in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + list(cbks)
    if not any(isinstance(k, LRScheduler) for k in cbks):
        cbks = [LRScheduler()] + list(cbks)
    from .. import observability as _obs
    if _obs.enabled() and \
            not any(isinstance(k, TelemetryCallback) for k in cbks):
        cbks = list(cbks) + [TelemetryCallback()]
    if save_dir and not any(isinstance(k, ModelCheckpoint) for k in cbks):
        cbks = list(cbks) + [ModelCheckpoint(save_freq, save_dir)]
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    metrics = metrics or []
    params = {"batch_size": batch_size, "epochs": epochs, "steps": steps,
              "verbose": verbose, "metrics": metrics}
    cbk_list.set_params(params)
    return cbk_list


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


class Callback:
    """hapi/callbacks.py Callback base: all hooks are no-ops."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class TelemetryCallback(Callback):
    """Feeds paddle_tpu.observability step metrics from the hapi fit loop:
    per-batch latency + examples/s (`paddle_tpu_step_latency_seconds{fn=
    hapi_train_batch}`), per-epoch device-memory gauges.  Auto-inserted by
    config_callbacks when telemetry is enabled; inert (records nothing)
    when it is off."""

    def __init__(self, fn: str = "hapi_train_batch"):
        super().__init__()
        self.fn = fn
        self._t0 = None
        self._span = None

    @staticmethod
    def _obs():
        from .. import observability
        return observability

    def on_train_batch_begin(self, step, logs=None):
        if self._obs().enabled():
            import time

            # batch span: the hapi fit loop shows up on the chrome-trace
            # timeline (and in the flight record) next to the compiled
            # step's own train_step spans
            self._span = self._obs().trace.span("hapi.train_batch",
                                                step=step)
            self._span.__enter__()
            self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        obs = self._obs()
        if self._span is not None:
            self._span.__exit__(None, None, None)
            self._span = None
        if not obs.enabled() or self._t0 is None:
            return
        import time
        dt = time.perf_counter() - self._t0
        self._t0 = None
        bs = (logs or {}).get("batch_size") or self.params.get("batch_size")
        obs.steps.record_step(dt, examples=bs, fn=self.fn)

    def on_epoch_end(self, epoch, logs=None):
        if self._obs().enabled():
            self._obs().steps.record_memory_stats()


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        names = []
        for m in self.params.get("metrics", []):
            n = m.name()
            names.extend(n if isinstance(n, (list, tuple)) else [n])
        self.train_metrics = ["loss"] + names

    def on_epoch_begin(self, epoch, logs=None):
        self.steps = self.params.get("steps")
        self.epoch = epoch
        self.train_step = 0
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")
        self.progbar = ProgressBar(num=self.steps, verbose=self.verbose)

    def _updates(self, logs):
        values = []
        for k in getattr(self, "train_metrics", ["loss"]):
            if k in (logs or {}):
                values.append((k, _scalar(logs[k])))
        return values

    def on_train_batch_end(self, step, logs=None):
        self.train_step += 1
        if self.verbose and self.train_step % self.log_freq == 0:
            self.progbar.update(self.train_step, self._updates(logs))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            self.progbar.update(self.train_step, self._updates(logs))

    def on_eval_begin(self, logs=None):
        self.eval_steps = (logs or {}).get("steps")
        self.eval_progbar = ProgressBar(num=self.eval_steps,
                                        verbose=self.verbose)
        if self.verbose:
            print("Eval begin...")

    def on_eval_batch_end(self, step, logs=None):
        if self.verbose and (step + 1) % self.log_freq == 0:
            self.eval_progbar.update(step + 1, self._updates(logs))

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = ", ".join(f"{k}: {v}" for k, v in (logs or {}).items()
                              if k != "batch_size")
            print(f"Eval samples done — {items}")


class ModelCheckpoint(Callback):
    """hapi/callbacks.py:534: save every `save_freq` epochs + final."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and self.save_dir and \
                epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


def restore_checkpoint_state(model, state) -> dict:
    """Apply a checkpoint tree (as written by CheckpointCallback) to a
    hapi Model: weights, optimizer state (scheduler scalars coerced back
    from their 0-d round-trip form), and the global RNG.  Returns the
    ``train`` block as python scalars (rng_key stays an array)."""
    import jax
    import jax.numpy as jnp

    from ..core import random as random_mod

    def as_int(v):
        return int(np.ravel(np.asarray(
            v.numpy() if hasattr(v, "numpy") else v))[0])

    model.network.set_state_dict(state["model"])
    if model._optimizer is not None and "optimizer" in state:
        opt_state = dict(state["optimizer"])
        lrs = opt_state.get("LR_Scheduler")
        if isinstance(lrs, dict):
            opt_state["LR_Scheduler"] = {
                k: (np.ravel(np.asarray(
                    v.numpy() if hasattr(v, "numpy") else v))[0].item()
                    if not isinstance(v, (numbers.Number, str)) else v)
                for k, v in lrs.items()}
        model._optimizer.set_state_dict(opt_state)
    train = state.get("train", {})
    if "rng_key" in train:
        from ..testing import faults
        faults.fault_point("restore.rng")
        raw = train["rng_key"]
        raw = raw.numpy() if hasattr(raw, "numpy") else raw
        key = jax.random.wrap_key_data(
            jnp.asarray(np.asarray(raw), jnp.uint32))
        random_mod.set_rng_state((key, as_int(train.get("rng_counter", 0))))
    return {k: (as_int(v) if k != "rng_key" else v)
            for k, v in train.items()}


class CheckpointCallback(Callback):
    """Validated-checkpoint save/resume for the fit loop (ISSUE 5).

    Writes sharded, CRC-validated, COMMITTED-marked checkpoints through
    :class:`~paddle_tpu.framework.checkpoint.AsyncCheckpointSaver`:
    model weights + optimizer state + a ``train`` scalar block (epoch,
    step-in-epoch, optimizer step count, RNG key/counter, dataloader
    epoch seed) — everything ``Model.fit(resume=...)`` needs to continue
    a killed run bit-identically.

    Saves every ``save_freq`` epochs, optionally every ``every_n_steps``
    batches (async: the fit loop never blocks on disk), and — the
    preemption path — a *blocking* emergency save at the first step
    boundary after ``framework.preemption`` flags a SIGTERM, after which
    ``model.stop_training`` ends the run cleanly.

    World-size awareness (elastic resume, ISSUE 6): ``dp_world_size`` is
    the data-parallel replica count this rank trains in (default: the
    launcher env / jax process count).  The ``train`` block then records
    the GLOBAL sample offset of the epoch (``samples_in_epoch`` =
    steps x per-rank batch x dp world) instead of only the per-rank step
    index, so ``Model.fit(resume=...)`` on a DIFFERENT topology can
    recompute the skip prefix in its own step units and preserve the
    global sample order.
    """

    def __init__(self, save_dir, save_freq=1, every_n_steps=None,
                 keep_last=3, fs=None, data_seed=0, dp_world_size=None):
        super().__init__()
        from ..framework.checkpoint import AsyncCheckpointSaver
        self.saver = AsyncCheckpointSaver(save_dir, keep_last=keep_last,
                                          fs=fs)
        self.save_freq = save_freq
        self.every_n_steps = every_n_steps
        self.data_seed = int(data_seed)
        if dp_world_size is None:
            from ..parallel import env as dist_env
            dp_world_size = max(1, dist_env.get_world_size())
        self.dp_world_size = int(dp_world_size)
        self.preempted = False
        self._epoch = 0
        self._global_step = 0

    # -- state assembly ------------------------------------------------------
    def _train_block(self, epoch, step_in_epoch):
        import jax

        from ..core import random as random_mod
        key, counter = random_mod.get_rng_state()
        block = {"epoch": int(epoch), "step_in_epoch": int(step_in_epoch),
                 "opt_step_count": int(getattr(
                     self.model._optimizer, "_step_count", 0)),
                 "rng_key": np.asarray(jax.random.key_data(key)),
                 "rng_counter": int(counter),
                 "data_seed": self.data_seed,
                 "dp_world_size": self.dp_world_size}
        per_rank_bs = self.params.get("batch_size")
        if per_rank_bs:
            # global offsets, not per-rank steps: the resume topology may
            # run a different dp world size / per-rank batch
            gbs = int(per_rank_bs) * self.dp_world_size
            block["global_batch_size"] = gbs
            block["samples_in_epoch"] = int(step_in_epoch) * gbs
        return block

    def _save(self, epoch, step_in_epoch, blocking=False):
        state = {"model": self.model.network.state_dict(),
                 "train": self._train_block(epoch, step_in_epoch)}
        if self.model._optimizer is not None:
            state["optimizer"] = self.model._optimizer.state_dict()
        self.saver.save(state, step=self._global_step, blocking=blocking)

    def restore_into(self, state):
        """Apply a loaded checkpoint tree to the model; returns the
        ``train`` scalar block (``Model.fit`` consumes epoch/step/rng)."""
        train = restore_checkpoint_state(self.model, state)
        if "data_seed" in train:
            self.data_seed = int(train["data_seed"])
        self._global_step = int(train.get("opt_step_count", 0))
        return train

    # -- hooks ---------------------------------------------------------------
    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_train_batch_end(self, step, logs=None):
        from ..framework import preemption
        from ..testing import faults
        self._global_step += 1
        faults.fault_point("train.step", step=self._global_step)
        if preemption.requested():
            self._save(self._epoch, step + 1, blocking=True)
            preemption.mark_saved(self._global_step)
            self.preempted = True
            self.model.stop_training = True
            return
        if self.every_n_steps and self._global_step % self.every_n_steps == 0:
            self._save(self._epoch, step + 1)

    def on_epoch_end(self, epoch, logs=None):
        if not self.preempted and (epoch + 1) % self.save_freq == 0:
            # epoch done: resume point is the NEXT epoch at step 0
            self._save(epoch + 1, 0)

    def on_train_end(self, logs=None):
        self.saver.wait()


class LRScheduler(Callback):
    """hapi/callbacks.py:599: step the optimizer's LRScheduler."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_lr", None) if opt else None
        return lr if hasattr(lr, "step") else None

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    """hapi/callbacks.py:690."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.baseline = baseline
        self.min_delta = abs(min_delta)
        self.wait_epoch = 0
        self.best_weights = None
        self.stopped_epoch = 0
        self.save_best_model = save_best_model
        if mode not in ("auto", "min", "max"):
            warnings.warn(f"EarlyStopping mode {mode} unknown, using 'auto'")
            mode = "auto"
        if mode == "min" or (mode == "auto" and "acc" not in monitor):
            self.monitor_op = np.less
            self.min_delta *= -1
        else:
            self.monitor_op = np.greater

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        self.epoch = 0
        if self.baseline is not None:
            self.best_value = self.baseline
        else:
            self.best_value = np.inf if self.monitor_op == np.less else -np.inf

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch

    def on_eval_end(self, logs=None):
        if logs is None or self.monitor not in logs:
            warnings.warn(f"Monitor of EarlyStopping should be loss or metric "
                          f"name; {self.monitor} missing in eval logs")
            return
        current = _scalar(logs[self.monitor])
        if self.monitor_op(current - self.min_delta, self.best_value):
            self.best_value = current
            self.wait_epoch = 0
            if self.save_best_model and self.model is not None:
                import copy
                self.best_weights = copy.deepcopy(
                    {k: v.numpy() for k, v in
                     self.model.network.state_dict().items()})
        else:
            self.wait_epoch += 1
        if self.wait_epoch >= self.patience:
            self.stopped_epoch = self.epoch
            self.model.stop_training = True
            if self.verbose > 0:
                print(f"Epoch {self.stopped_epoch + 1}: early stopping")

    def on_train_end(self, logs=None):
        # restore the best weights seen during training (reference saves the
        # best model to save_dir; without a dir we restore in place)
        if self.save_best_model and self.best_weights is not None and \
                self.model is not None:
            self.model.network.set_state_dict(self.best_weights)


class ReduceLROnPlateau(Callback):
    """hapi/callbacks.py:960: scale LR by `factor` after `patience` epochs
    without improvement."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        if factor >= 1.0:
            raise ValueError("ReduceLROnPlateau does not support factor >= 1")
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.cooldown_counter = 0
        self.wait = 0
        if mode == "min" or (mode == "auto" and "acc" not in monitor):
            self.monitor_op = lambda a, b: np.less(a, b - self.min_delta)
            self.best = np.inf
        else:
            self.monitor_op = lambda a, b: np.greater(a, b + self.min_delta)
            self.best = -np.inf

    def on_eval_end(self, logs=None):
        if logs is None or self.monitor not in logs:
            warnings.warn(f"Monitor {self.monitor} missing in eval logs")
            return
        current = _scalar(logs[self.monitor])
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self.monitor_op(current, self.best):
            self.best = current
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None:
                    old_lr = opt.get_lr()
                    new_lr = max(old_lr * self.factor, self.min_lr)
                    if old_lr - new_lr > 1e-12:
                        try:
                            opt.set_lr(new_lr)
                            if self.verbose:
                                print(f"ReduceLROnPlateau: lr {old_lr} -> "
                                      f"{new_lr}")
                        except RuntimeError:
                            warnings.warn(
                                "ReduceLROnPlateau cannot override an "
                                "LRScheduler-driven optimizer; skipping")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """hapi/callbacks.py:844 — VisualDL isn't installed in this build; logs
    scalars to a jsonl file under log_dir instead (same call pattern)."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self.epochs = None
        self.steps = None
        self.epoch = 0
        os.makedirs(log_dir, exist_ok=True)
        self._file = None

    def _log(self, tag, values, step):
        import json
        if self._file is None:
            self._file = open(os.path.join(self.log_dir, "scalars.jsonl"),
                              "a", buffering=1)
        for k, v in (values or {}).items():
            if not isinstance(v, numbers.Number):
                try:
                    v = _scalar(v)
                except (TypeError, ValueError):
                    continue
            self._file.write(json.dumps({"tag": f"{tag}/{k}",
                                         "value": float(v),
                                         "step": int(step)}) + "\n")

    def on_train_end(self, logs=None):
        if self._file is not None:
            self._file.close()
            self._file = None

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch

    def on_train_batch_end(self, step, logs=None):
        self._log("train_batch", logs, step)

    def on_epoch_end(self, epoch, logs=None):
        self._log("train", logs, epoch)

    def on_eval_end(self, logs=None):
        self._log("eval", logs, self.epoch)
