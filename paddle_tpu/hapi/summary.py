"""paddle.summary — parity with python/paddle/hapi/model_summary.py: layer
table with output shapes and parameter counts via forward hooks."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    rows = []
    hooks = []

    def register(layer, prefix):
        children = list(layer.named_children()) if \
            hasattr(layer, "named_children") else \
            list(layer._sub_layers.items())
        if not children:
            def hook(l, inputs, outputs, name=prefix, lay=layer):
                out = outputs[0] if isinstance(outputs, (list, tuple)) \
                    else outputs
                shape = list(out.shape) if hasattr(out, "shape") else None
                n_params = int(sum(np.prod(p.shape)
                                   for p in lay.parameters(include_sublayers=False))) \
                    if hasattr(lay, "parameters") else 0
                rows.append((name or type(lay).__name__,
                             type(lay).__name__, shape, n_params))
            hooks.append(layer.register_forward_post_hook(hook))
        for name, child in children:
            register(child, f"{prefix}.{name}" if prefix else name)

    register(net, "")

    if input is not None:
        x = input
    else:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = input_size if isinstance(input_size, list) else [input_size]
        import jax.numpy as jnp
        xs = []
        for i, s in enumerate(sizes):
            dt = (dtypes[i] if isinstance(dtypes, (list, tuple)) else dtypes) \
                or "float32"
            xs.append(Tensor(jnp.zeros(tuple(s), dtype=dt), _internal=True))
        x = xs if len(xs) > 1 else xs[0]

    was_training = net.training
    net.eval()
    try:
        net(*x) if isinstance(x, list) else net(x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = int(sum(np.prod(p.shape) for p in net.parameters()))
    trainable = int(sum(np.prod(p.shape) for p in net.parameters()
                        if not p.stop_gradient))
    width = 76
    print("-" * width)
    print(f"{'Layer (type)':<36}{'Output Shape':<24}{'Param #':<12}")
    print("=" * width)
    for name, cls, shape, n in rows:
        print(f"{(name + ' (' + cls + ')')[:35]:<36}"
              f"{str(shape)[:23]:<24}{n:<12}")
    print("=" * width)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print("-" * width)
    return {"total_params": total, "trainable_params": trainable}
