"""hapi Model — parity with python/paddle/hapi/model.py:915 (prepare:1499,
fit, evaluate, predict, train_batch/eval_batch/predict_batch, save/load).

The reference maintains dual static/dygraph engines; here there is one eager
engine whose hot math is jit-compiled underneath by the op layer, and the
distributed path goes through fleet/spmd (prepare_distributed_context ≈
model.py:189 is subsumed by fleet.distributed_model)."""
from __future__ import annotations

import os
import warnings

import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..framework.io import load as _load, save as _save
from ..io.dataloader import DataLoader
from . import callbacks as callbacks_mod


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _to_tensor(x):
    if isinstance(x, Tensor):
        return x
    import jax.numpy as jnp
    return Tensor(jnp.asarray(np.asarray(x)), _internal=True)


class DeviceLossList:
    """Per-batch losses kept as device arrays — the dispatch-ahead loss
    path (ISSUE 4).  ``train_batch``/``_eval_batch_impl`` used to force a
    host sync per loss element (``float(np.asarray(l.numpy()).ravel()[0])``
    each); this list defers the fetch entirely and gathers the WHOLE list
    with one ``jax.device_get`` the first time a consumer needs floats
    (``float()``, indexing, iteration, ``np.asarray``).  A fit loop whose
    callbacks only read losses at ``log_freq``/epoch end therefore
    dispatches K steps ahead of the device instead of round-tripping each
    one."""

    __slots__ = ("_arrays", "_host")

    def __init__(self, arrays):
        self._arrays = list(arrays)
        self._host = None

    @property
    def fetched(self) -> bool:
        return self._host is not None

    def _fetch(self):
        if self._host is None:
            import jax
            vals = jax.device_get(self._arrays)
            self._host = [float(np.ravel(np.asarray(v))[0]) for v in vals]
        return self._host

    def __len__(self):
        return len(self._arrays)

    def __bool__(self):
        return bool(self._arrays)

    def __iter__(self):
        return iter(self._fetch())

    def __getitem__(self, i):
        return self._fetch()[i]

    def __float__(self):
        return float(self._fetch()[0])

    def __array__(self, dtype=None, copy=None):
        a = np.asarray(self._fetch())
        return a if dtype is None else a.astype(dtype)

    def __repr__(self):
        if self._host is None:
            return f"DeviceLossList(<{len(self._arrays)} unfetched>)"
        return repr(self._host)


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        """model.py:1499 parity."""
        self._optimizer = optimizer
        if loss is not None and not callable(loss):
            raise TypeError("loss must be a callable (Layer or function)")
        self._loss = loss
        self._metrics = _to_list(metrics)
        self._amp_configs = amp_configs
        return self

    # -- batch-level ---------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        inputs = [_to_tensor(t) for t in _to_list(inputs)]
        labels = [_to_tensor(t) for t in _to_list(labels)]
        self.network.train()
        outputs = self.network(*inputs)
        outs = _to_list(outputs)
        losses = self._loss(*(outs + labels)) if self._loss else outputs
        loss_list = _to_list(losses)
        total = loss_list[0]
        for extra in loss_list[1:]:
            total = total + extra
        total.backward()
        if update and self._optimizer is not None:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(*_to_list(m.compute(*(outs + labels))))
            metrics.append(m.accumulate())
        # losses stay on device; one gather when a consumer reads them
        out_loss = DeviceLossList(
            [l._value if isinstance(l, Tensor) else l for l in loss_list])
        return (out_loss, metrics) if metrics else out_loss

    @no_grad()
    def _eval_batch_impl(self, inputs, labels=None):
        """Always returns (loss_list, metrics) so log packing can't confuse
        metric values for losses."""
        inputs = [_to_tensor(t) for t in _to_list(inputs)]
        labels = [_to_tensor(t) for t in _to_list(labels)]
        self.network.eval()
        outputs = self.network(*inputs)
        outs = _to_list(outputs)
        loss_list = []
        if self._loss:
            losses = self._loss(*(outs + labels))
            loss_list = DeviceLossList(
                [l._value if isinstance(l, Tensor) else l
                 for l in _to_list(losses)])
        metrics = []
        for m in self._metrics:
            m.update(*_to_list(m.compute(*(outs + labels))))
            metrics.append(m.accumulate())
        return loss_list, metrics

    def eval_batch(self, inputs, labels=None):
        loss_list, metrics = self._eval_batch_impl(inputs, labels)
        if loss_list and metrics:
            return loss_list, metrics
        return loss_list if loss_list else metrics

    @no_grad()
    def predict_batch(self, inputs):
        inputs = [_to_tensor(t) for t in _to_list(inputs)]
        self.network.eval()
        outputs = self.network(*inputs)
        return [o.numpy() for o in _to_list(outputs)]

    # -- loops ---------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle, num_workers,
                drop_last=False, prefetch=False, prefetch_depth=2):
        from ..io.prefetch import DevicePrefetcher
        if isinstance(data, DevicePrefetcher):
            return data
        if data is None:
            return None
        loader = data if isinstance(data, DataLoader) else DataLoader(
            data, batch_size=batch_size, shuffle=shuffle,
            num_workers=num_workers, drop_last=drop_last)
        if prefetch:
            return DevicePrefetcher(loader, depth=prefetch_depth,
                                    name="hapi_fit")
        return loader

    @staticmethod
    def _split_batch(batch):
        batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
        if len(batch) == 1:
            return batch, []
        return batch[:-1], batch[-1:]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, prefetch=False,
            prefetch_depth=2, resume=None):
        """model.py fit parity: epoch/step loops with the callback protocol.

        `prefetch=True` routes the train loader through a DevicePrefetcher
        (`prefetch_depth` batches kept device-resident ahead of the loop);
        combined with the deferred DeviceLossList losses the loop dispatches
        ahead of the device instead of syncing per batch.  A pre-built
        DevicePrefetcher may also be passed directly as `train_data`.

        `resume="auto"` restores the latest valid checkpoint written by a
        :class:`~paddle_tpu.hapi.callbacks.CheckpointCallback` (which must
        be in `callbacks`) and continues from the recorded epoch/step with
        the saved optimizer state and RNG — bit-identical to the
        uninterrupted run; `resume=<path>` loads an explicit checkpoint
        step dir (or walks a checkpoint base dir).  While fitting, SIGTERM
        /SIGINT request an emergency checkpoint at the next step boundary
        (framework.preemption) instead of killing the run."""
        assert train_data is not None, "train_data must be given!"
        loader = self._loader(train_data, batch_size, shuffle, num_workers,
                              drop_last=drop_last, prefetch=prefetch,
                              prefetch_depth=prefetch_depth)
        eval_loader = self._loader(eval_data, batch_size, False, num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        # per-rank batch size (world-size-aware checkpoints record global
        # sample offsets = steps x this x dp world); a pre-built loader
        # (or the one inside a DevicePrefetcher) carries its own
        per_rank_bs = getattr(loader, "batch_size", None) or getattr(
            getattr(loader, "data", None), "batch_size", None)
        cbks = callbacks_mod.config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            batch_size=per_rank_bs,
            log_freq=log_freq, verbose=verbose, save_freq=save_freq,
            save_dir=save_dir, metrics=self._metrics)
        ckpt_cb = next((c for c in cbks.callbacks if isinstance(
            c, callbacks_mod.CheckpointCallback)), None)
        start_epoch = start_step = 0
        if resume:
            start_epoch, start_step = self._restore_for_resume(
                resume, ckpt_cb, per_rank_bs)

        from ..framework import preemption
        self.stop_training = False
        cbks.on_train_begin({})
        with preemption.guard():
            for epoch in range(start_epoch, epochs):
                if self.stop_training:
                    break
                if ckpt_cb is not None:
                    # deterministic per-epoch shuffle: a resumed run must
                    # draw the SAME permutation this epoch saw originally
                    np.random.seed((ckpt_cb.data_seed + epoch) % (2 ** 32))
                cbks.on_epoch_begin(epoch, {})
                for m in self._metrics:
                    m.reset()
                logs = {}
                pending_update = False
                skip = start_step if epoch == start_epoch else 0
                for step, batch in enumerate(loader):
                    if step < skip:
                        continue  # replayed prefix of a resumed epoch
                    cbks.on_train_batch_begin(step, {})
                    ins, lbs = self._split_batch(batch)
                    update = (step + 1) % accumulate_grad_batches == 0
                    res = self.train_batch(ins, lbs, update=update)
                    pending_update = not update
                    logs = self._pack_logs(res)
                    cbks.on_train_batch_end(step, logs)
                    if self.stop_training:
                        break  # preempted: checkpoint already on disk
                    if num_iters is not None and step + 1 >= num_iters:
                        break
                if self.stop_training:
                    break
                if pending_update and self._optimizer is not None:
                    # flush a trailing partial accumulation group so grads
                    # never leak across epochs
                    self._optimizer.step()
                    self._optimizer.clear_grad()
                cbks.on_epoch_end(epoch, logs)

                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    eval_logs = self._run_eval(eval_loader, cbks)
                    cbks.on_eval_end(eval_logs)
        cbks.on_train_end({})

    def _restore_for_resume(self, resume, ckpt_cb, per_rank_bs=None):
        """Resolve `resume` ("auto" | checkpoint dir) to a restored state;
        returns (start_epoch, start_step_in_epoch).

        World-size-aware (elastic) resume: when the checkpoint carries a
        global sample offset (``samples_in_epoch``) and this run's global
        batch (per-rank batch x the CheckpointCallback's ``dp_world_size``)
        is known, the skip prefix is recomputed in the NEW topology's step
        units — the epoch permutation is drawn dataset-level from
        ``data_seed + epoch``, so the global sample order is preserved
        across a dp world-size change.  A sample offset the new global
        batch cannot hit raises :class:`ElasticResumeError` instead of
        silently replaying from a misaligned sample."""
        from ..framework.checkpoint import (AsyncCheckpointSaver, _MANIFEST,
                                            ElasticResumeError, load_sharded)
        if resume == "auto":
            if ckpt_cb is None:
                raise ValueError(
                    "fit(resume='auto') needs a CheckpointCallback in "
                    "callbacks= (it owns the checkpoint directory)")
            _, state = ckpt_cb.saver.restore_latest_valid()
            if state is None:
                return 0, 0  # nothing saved yet: fresh start
        elif os.path.isfile(os.path.join(str(resume), _MANIFEST)):
            state = load_sharded(str(resume))
        else:
            _, state = AsyncCheckpointSaver(
                str(resume)).restore_latest_valid()
            if state is None:
                raise FileNotFoundError(
                    f"no valid checkpoint under {resume!r}")
        train = (ckpt_cb.restore_into(state) if ckpt_cb is not None
                 else callbacks_mod.restore_checkpoint_state(self, state))
        start_epoch = int(train.get("epoch", 0))
        start_step = int(train.get("step_in_epoch", 0))
        samples = train.get("samples_in_epoch")
        if samples is not None and ckpt_cb is not None and per_rank_bs:
            new_global = int(per_rank_bs) * ckpt_cb.dp_world_size
            samples = int(samples)
            if samples % new_global:
                raise ElasticResumeError(
                    f"elastic resume: checkpoint stopped at global sample "
                    f"offset {samples} of the epoch (written at global "
                    f"batch {train.get('global_batch_size')}, dp world "
                    f"{train.get('dp_world_size')}), which this topology's "
                    f"global batch {new_global} (= {per_rank_bs} x dp "
                    f"world {ckpt_cb.dp_world_size}) cannot reach",
                    samples=samples, global_batch_size=new_global)
            start_step = samples // new_global
        return start_epoch, start_step

    def _pack_logs(self, res):
        logs = {}
        if isinstance(res, tuple):
            loss_list, metrics = res
        else:
            loss_list, metrics = res, []
        if loss_list:
            logs["loss"] = loss_list
        for m, v in zip(self._metrics, metrics):
            name = m.name()
            if isinstance(name, (list, tuple)):
                vals = v if isinstance(v, (list, tuple, np.ndarray)) else [v]
                for n_, v_ in zip(name, vals):
                    logs[n_] = v_
            else:
                logs[name] = v
        return logs

    def _run_eval(self, eval_loader, cbks):
        for m in self._metrics:
            m.reset()
        steps = len(eval_loader) if hasattr(eval_loader, "__len__") else None
        cbks.on_eval_begin({"steps": steps})
        logs = {}
        for step, batch in enumerate(eval_loader):
            cbks.on_eval_batch_begin(step, {})
            ins, lbs = self._split_batch(batch)
            logs = self._pack_logs(self._eval_batch_impl(ins, lbs))
            cbks.on_eval_batch_end(step, logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None,
                 prefetch=False, prefetch_depth=2):
        loader = self._loader(eval_data, batch_size, False, num_workers,
                              prefetch=prefetch,
                              prefetch_depth=prefetch_depth)
        cbks = callbacks_mod.config_callbacks(
            callbacks, model=self, log_freq=log_freq, verbose=verbose,
            metrics=self._metrics, mode="eval")
        for m in self._metrics:
            m.reset()
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks.on_eval_begin({"steps": steps})
        logs = {}
        for step, batch in enumerate(loader):
            ins, lbs = self._split_batch(batch)
            logs = self._pack_logs(self._eval_batch_impl(ins, lbs))
            cbks.on_eval_batch_end(step, logs)
            if num_iters is not None and step + 1 >= num_iters:
                break
        cbks.on_eval_end(logs)
        result = {}
        if "loss" in logs:
            # materialize here (one gather): evaluate() returns plain floats
            result["loss"] = [float(v) for v in logs["loss"]]
        for m in self._metrics:
            name = m.name()
            result[name if not isinstance(name, list) else name[0]] = \
                m.accumulate()
        return result

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._loader(test_data, batch_size, False, num_workers)
        cbks = callbacks_mod.config_callbacks(
            callbacks, model=self, verbose=verbose, mode="predict")
        cbks.on_predict_begin({})
        outputs = []
        for step, batch in enumerate(loader):
            ins, _ = self._split_batch(batch)
            # a loss-prepared model treats the trailing field as the label;
            # otherwise every field is an input (reference: predict uses
            # declared inputs when given, else the whole sample)
            use_ins = (self._labels is not None or self._loss is not None)
            outs = self.predict_batch(ins if use_ins else list(batch))
            outputs.append(outs)
            cbks.on_predict_batch_end(step, {})
        cbks.on_predict_end({})
        # regroup: list over outputs, each a list over batches
        n_out = len(outputs[0]) if outputs else 0
        grouped = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g, axis=0) for g in grouped]
        return grouped

    # -- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        """model.py save parity: `path.pdparams` (+ `.pdopt` when training)."""
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        param_path = path if path.endswith(".pdparams") else path + ".pdparams"
        state = _load(param_path)
        if skip_mismatch:
            own = self.network.state_dict()
            filtered = {}
            for k, v in state.items():
                if k in own and tuple(own[k].shape) == tuple(v.shape):
                    filtered[k] = v
                else:
                    warnings.warn(f"skip loading {k} (missing or mismatched)")
            state = filtered
        self.network.set_state_dict(state)
        opt_path = path.replace(".pdparams", "") + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(_load(opt_path))

    # -- misc ----------------------------------------------------------------
    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtype)
