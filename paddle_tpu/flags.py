"""Runtime flag system — parity with the reference's exported gflags
(paddle/fluid/platform/flags.cc: 74 `PADDLE_DEFINE_EXPORTED_*` flags surfaced
via paddle.set_flags/get_flags and FLAGS_* env vars,
global_value_getter_setter.cc).

TPU build: flags that governed CUDA allocators/cuDNN (allocator_strategy,
cudnn_deterministic, gpu memory fractions, ...) are accepted and RECORDED
ONLY — XLA owns those concerns.  Behavioral flags that are wired:
  FLAGS_check_nan_inf  — per-op output NaN/Inf scan in the eager op layer
                         (nan_inf_utils_detail.cc:341 parity; jax pairs it
                         with jax_debug_nans for in-jit checks)
  FLAGS_telemetry      — paddle_tpu.observability: op-dispatch counters,
                         retrace sentinel, step metrics (also enabled by
                         the PADDLE_TPU_TELEMETRY=1 env var)

Every set_flags() change is also recorded into the always-on flight
recorder (observability/flight.py), so a crash dump names the behavioral
flags (and, via core/op.py, the op that tripped FLAGS_check_nan_inf) that
were live when the process died.
"""
from __future__ import annotations

import os

_FLAGS: dict[str, object] = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_telemetry": False,
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_use_pinned_memory": True,
    "FLAGS_benchmark": False,
    "FLAGS_paddle_num_threads": 1,
    "FLAGS_max_inplace_grad_add": 0,
    "FLAGS_conv_workspace_size_limit": 512,
    "FLAGS_cudnn_exhaustive_search": False,
    "FLAGS_selected_devices": "",
}


def _coerce(old, value):
    if isinstance(old, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if isinstance(old, int) and not isinstance(old, bool):
        return int(value)
    if isinstance(old, float):
        return float(value)
    return value


def _bootstrap_from_env():
    for key in list(_FLAGS):
        if key in os.environ:
            _FLAGS[key] = _coerce(_FLAGS[key], os.environ[key])
    if _FLAGS["FLAGS_check_nan_inf"]:
        _sync_check_nan_inf()
    if _FLAGS["FLAGS_telemetry"]:
        _sync_telemetry()


def set_flags(flags: dict):
    """paddle.set_flags parity (unknown flags raise, like the reference)."""
    for k, v in flags.items():
        key = k if k.startswith("FLAGS_") else f"FLAGS_{k}"
        if key not in _FLAGS:
            raise ValueError(f"unknown flag {k!r}")
        _FLAGS[key] = _coerce(_FLAGS[key], v)
        try:  # config provenance for crash dumps; never a set_flags failure
            from .observability import flight
            flight.record("flag", key, value=str(_FLAGS[key]))
        except Exception:
            pass
        if key == "FLAGS_check_nan_inf":
            _sync_check_nan_inf()
        if key == "FLAGS_telemetry":
            _sync_telemetry()


def get_flags(flags):
    """paddle.get_flags parity: str or list → dict."""
    keys = [flags] if isinstance(flags, str) else list(flags)
    out = {}
    for k in keys:
        key = k if k.startswith("FLAGS_") else f"FLAGS_{k}"
        if key not in _FLAGS:
            raise ValueError(f"unknown flag {k!r}")
        out[key] = _FLAGS[key]
    return out


def _sync_check_nan_inf():
    from .core import op as op_mod
    op_mod.CHECK_NAN_INF = bool(_FLAGS["FLAGS_check_nan_inf"])


def _sync_telemetry():
    from . import observability
    observability.enable(bool(_FLAGS["FLAGS_telemetry"]))


_bootstrap_from_env()
