"""paddle.quantization parity (reference: python/paddle/static/quantization
post-training + QAT passes, and the paddle.quantization QAT config API).

TPU-native scope: simulated int8 quantization.  QAT inserts fake-quant
(quantize-dequantize with a straight-through estimator) on weights and
activations of Linear/Conv2D; PTQ observes abs-max ranges on calibration
batches (observation is independent of train/eval mode).  `convert` bakes
weight quantization onto the int8 grid and freezes the observers — the
quant/dequant ops stay in the inference graph with the calibrated scales,
matching the reference's converted-program shape.  The reference's int8
GEMM kernels (cuDNN/oneDNN) have no public TPU analog, so compute stays in
float with quantized values — the standard simulated-quant formulation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.op import apply_op
from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMaxObserver",
           "adaround_weight",
           "HistObserver", "cal_kl_threshold", "quant_dequant",
           "QuantedLinear", "QuantedConv2D"]


# -- fake quant with straight-through estimator ------------------------------

def _qdq(x, scale, qmax):
    """The one quantize-dequantize formula (scalar or per-channel scale)."""
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    return q * scale / qmax


@jax.custom_vjp
def _fake_quant(x, scale, qmax):
    return _qdq(x, scale, qmax)


def _fq_fwd(x, scale, qmax):
    return _fake_quant(x, scale, qmax), (x, scale)


def _fq_bwd(res, g):
    x, scale = res
    # STE: pass gradients through inside the clip range, zero outside
    inside = (jnp.abs(x) <= scale).astype(g.dtype)
    return g * inside, jnp.zeros_like(scale), None


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


def quant_dequant(x, scale, bits=8):
    """Simulated quantization op (fake_quantize_dequantize_abs_max)."""
    qmax = float(2 ** (bits - 1) - 1)

    def raw(v, s):
        return _fake_quant(v, jnp.maximum(s, 1e-8), qmax)

    return apply_op(raw, "fake_quantize_dequantize", (x, scale), {})


class FakeQuanterWithAbsMaxObserver(Layer):
    """QAT quanter: tracks a running abs-max and fake-quants through it
    (reference FakeQuanterWithAbsMaxObserverLayer).  Observation is gated by
    `observing`, NOT the train/eval flag, so the standard PTQ flow
    (net.eval() before calibration) still collects statistics; convert()
    freezes it."""

    def __init__(self, moving_rate=0.9, bit_length=8, name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self.register_buffer("scale", Tensor(jnp.ones((), jnp.float32)))
        self._seen = False
        self.observing = True

    def forward(self, x):
        if self.observing:
            if isinstance(x._value, jax.core.Tracer):
                if not self._seen:
                    import warnings
                    warnings.warn(
                        "quant observer ran only under jit: calibration "
                        "needs eager forwards (scale stays at init)")
            else:
                self._observe_value(x._value)
        return quant_dequant(x, self.scale, bits=self.bit_length)

    def _observe_value(self, xv):
        """EMA of batch abs-maxes, kept ENTIRELY on device: the abs-max
        reduce, the blend and the stored scale are device values, so an
        observed forward no longer blocks on a host transfer (was two
        per batch).  Subclasses that need the full distribution
        (HistObserver) override this."""
        cur = (jnp.max(jnp.abs(xv)).astype(jnp.float32) if xv.size
               else jnp.zeros((), jnp.float32))
        new = cur if not self._seen else \
            self.moving_rate * self.scale._value + \
            (1 - self.moving_rate) * cur
        self.scale._replace_(jnp.asarray(new, jnp.float32), None)
        self._seen = True


class _QuantedWrapper(Layer):
    """Wraps a Linear/Conv2D: fake-quant activation + weight, then run the
    original layer with the quantized weight."""

    def __init__(self, inner, a_quanter=None, w_bits=8, w_per_channel=False):
        super().__init__()
        self.inner = inner
        self.activation_quanter = a_quanter
        self.w_bits = w_bits
        self.w_per_channel = w_per_channel
        # set to [] by PTQ(weight_rounding="adaround"): calibration inputs
        # stashed for the convert()-time rounding optimization
        self._stash = None

    def _wq(self):
        w = self.inner.weight
        qmax = float(2 ** (self.w_bits - 1) - 1)
        per_channel = self.w_per_channel
        axis = _channel_axis(self.inner)

        # STE at the wrapper level: quantization is identity for grads
        def raw_ste(wv):
            s = _weight_scales(wv, per_channel, axis)
            return wv + jax.lax.stop_gradient(_qdq(wv, s, qmax) - wv)

        return apply_op(raw_ste, "weight_quantize", (w,), {})

    def forward(self, x):
        if self._stash is not None and len(self._stash) < 4 and \
                not isinstance(x._value, jax.core.Tracer):
            # PRE-quant input: adaround re-applies the activation quanter
            # at convert() time, when its scale is FINALIZED — the interim
            # running scale here would mis-train the rounding
            self._stash.append(x.detach())
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self._wq()
        return self._call_with_weight(x, w)


class QuantedLinear(_QuantedWrapper):
    def _call_with_weight(self, x, w):
        from ..nn import functional as F
        return F.linear(x, w, self.inner.bias)


class QuantedConv2D(_QuantedWrapper):
    def _call_with_weight(self, x, w):
        from ..nn import functional as F
        i = self.inner
        return F.conv2d(x, w, i.bias, i._stride, i._padding, i._dilation,
                        i._groups, i._data_format)


class QuantConfig:
    """paddle.quantization.QuantConfig parity (subset: global activation /
    weight quanter factories)."""

    def __init__(self, activation=None, weight=None, activation_bits=8,
                 weight_bits=8, weight_quantize_type="abs_max"):
        self.activation = activation
        if weight is not None:
            raise NotImplementedError(
                "custom weight quanters are not supported; weights use "
                "abs-max fake quant at weight_bits precision")
        if weight_quantize_type not in ("abs_max", "channel_wise_abs_max"):
            raise ValueError(
                f"unknown weight_quantize_type {weight_quantize_type!r}")
        self.activation_bits = activation_bits
        self.weight_bits = weight_bits
        self.weight_quantize_type = weight_quantize_type

    def add_layer_config(self, *a, **kw):
        pass  # per-layer overrides not needed for the subset

    def _make_act_quanter(self):
        import copy

        if self.activation is None:
            return FakeQuanterWithAbsMaxObserver(
                bit_length=self.activation_bits)
        if isinstance(self.activation, type):
            return self.activation()
        # instance template: each wrapped layer needs its OWN observer
        return copy.deepcopy(self.activation)


def _swap_layers(model, factory):
    """Replace Linear/Conv2D sublayers via `factory(layer)` (in place)."""
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import Conv2D

    for layer in model.sublayers(include_self=True):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, (Linear, Conv2D)) and \
                    not isinstance(sub, _QuantedWrapper):
                layer._sub_layers[name] = factory(sub)
    return model


class QAT:
    """Quantization-aware training driver (reference QAT class)."""

    def __init__(self, config: QuantConfig | None = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=True):
        from ..nn.layer.common import Linear

        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def factory(sub):
            cls = QuantedLinear if isinstance(sub, Linear) else QuantedConv2D
            return cls(sub, self.config._make_act_quanter(),
                       w_bits=self.config.weight_bits,
                       w_per_channel=(self.config.weight_quantize_type ==
                                      "channel_wise_abs_max"))

        return _swap_layers(model, factory)

    def convert(self, model, inplace=True):
        """Freeze for inference: bake weight quantization into the stored
        weights and STOP observing — the quant/dequant ops stay in the graph
        with the calibrated activation scales (reference converted-program
        semantics)."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        # pass 1: finalize + freeze every observer FIRST, so the learned
        # rounding below sees the final activation scales, not the interim
        # running abs-max used during calibration
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, HistObserver):
                layer.finalize()      # histogram -> calibrated threshold
            if isinstance(layer, FakeQuanterWithAbsMaxObserver):
                layer.observing = False
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, _QuantedWrapper):
                stash, layer._stash = layer._stash, None  # stop stashing
                if stash:
                    # learned rounding on stashed calibration inputs
                    layer.inner.weight._replace_(
                        adaround_weight(layer, stash), None)
                    continue
                qmax = float(2 ** (layer.w_bits - 1) - 1)
                wv = layer.inner.weight._value
                s = _weight_scales(wv, layer.w_per_channel,
                                   _channel_axis(layer.inner))
                layer.inner.weight._replace_(_qdq(wv, s, qmax), None)
        return model


class PTQ(QAT):
    """Post-training quantization: quantize(), run calibration batches (any
    train/eval mode — observers watch until convert), then convert().

    `algo` selects the activation calibrator (reference
    post_training_quantization.py): 'kl' (default; cal_kl_threshold),
    'hist' (percentile), 'mse', 'avg', 'abs_max'.  `weight_quantize_type`
    'channel_wise_abs_max' enables per-output-channel weight scales."""

    _DEFAULT_CAL = ("kl", 2048, 0.99999, "channel_wise_abs_max")

    def __init__(self, config: QuantConfig | None = None, algo="kl",
                 bins=2048, percent=0.99999,
                 weight_quantize_type="channel_wise_abs_max",
                 weight_rounding="nearest"):
        if weight_rounding not in ("nearest", "adaround"):
            raise ValueError(
                f"unknown weight_rounding {weight_rounding!r}")
        self.weight_rounding = weight_rounding
        if config is not None:
            if (algo, bins, percent, weight_quantize_type) != \
                    self._DEFAULT_CAL:
                raise ValueError(
                    "pass EITHER an explicit QuantConfig or calibration "
                    "kwargs (algo/bins/percent/weight_quantize_type), not "
                    "both — the config would silently win")
        else:
            # every algo incl. abs_max goes through HistObserver: PTQ
            # abs_max means the GLOBAL max over calibration (reference
            # semantics), not the QAT moving average
            act = HistObserver(algo=algo, bins=bins, percent=percent)
            config = QuantConfig(
                activation=act, weight_quantize_type=weight_quantize_type)
        super().__init__(config)

    def quantize(self, model, inplace=True):
        model = super().quantize(model, inplace=inplace)
        if self.weight_rounding == "adaround":
            for layer in model.sublayers(include_self=True):
                if isinstance(layer, _QuantedWrapper):
                    layer._stash = []
        return model


# -- PTQ calibration depth (round-4; reference slim/quantization:
# post_training_quantization.py algos {KL, hist, mse, avg, abs_max} +
# cal_kl_threshold.py, channel-wise weight quantization) ----------------------

def cal_kl_threshold(hist, bin_width, bits=8):
    """TensorRT-style KL calibration (reference cal_kl_threshold.py:75):
    pick the clip threshold whose 2^(bits-1)-1-level quantized distribution
    has minimum KL divergence from the clipped reference distribution.
    `hist` bins |x| from 0 with width `bin_width`; returns the threshold."""
    hist = np.asarray(hist, np.float64).copy()
    nbins = len(hist)
    levels = 2 ** (bits - 1) - 1
    # drop the zero bin (TensorRT/MXNet detail): exact zeros — half of any
    # post-ReLU tensor — quantize losslessly at EVERY threshold, but left
    # in the histogram their spike dominates the divergence and rewards
    # clipping away real mass (the spike stays sharp when fewer source
    # bins merge per level, so small thresholds looked spuriously good)
    hist[0] = 0.0
    # search from `levels` bins upward (TensorRT's original start): the
    # reference starts at nbins/2, which can never clip below half the
    # histogram range and so fails exactly when outliers inflate the range
    csum = np.concatenate([[0.0], np.cumsum(hist)])
    nzsum = np.concatenate([[0], np.cumsum(hist > 0)])
    total = csum[-1]
    best_i, best_kl = nbins, np.inf
    for i in range(levels, nbins + 1):
        tail = total - csum[i]
        if hist[i - 1] == 0 and tail != 0:
            # clipped mass would fold onto an EMPTY edge bin: no quantizer
            # level represents it, so the divergence is infinite (the
            # masked KL below would instead silently drop the folded mass,
            # making aggressive clipping look free)
            continue
        if hist[i - 1] == 0 and tail == 0:
            continue
        p = hist[:i].copy()
        p[i - 1] += tail                    # fold outliers into the edge
        # quantize the first i bins down to `levels` merged bins, then
        # expand back, spreading each merged mass over its NONZERO source
        # bins (all vectorized: cumsum differences + searchsorted)
        edges = (np.arange(levels + 1) * i) // levels   # strictly increasing
        merged = csum[edges[1:]] - csum[edges[:-1]]
        nnz_per = (nzsum[edges[1:]] - nzsum[edges[:-1]]).astype(np.float64)
        k_of_j = np.searchsorted(edges, np.arange(i), side="right") - 1
        fill = np.divide(merged[k_of_j], nnz_per[k_of_j],
                         out=np.zeros(i), where=nnz_per[k_of_j] > 0)
        q = np.where(hist[:i] > 0, fill, 0.0)
        psum, qsum = p.sum(), q.sum()
        if psum == 0 or qsum == 0:
            continue
        p /= psum
        q /= qsum
        mask = (p > 0) & (q > 0)
        kl = float(np.sum(p[mask] * np.log(p[mask] / q[mask])))
        if kl < best_kl:
            best_kl, best_i = kl, i
    return best_i * bin_width


class HistObserver(FakeQuanterWithAbsMaxObserver):
    """Histogram-calibrated activation observer
    (reference post_training_quantization.py algo= 'KL' | 'hist' | 'mse' |
    'avg' | 'abs_max').  Accumulates an adaptive-range histogram of |x|
    over calibration batches; ``finalize()`` (called by convert()) turns it
    into the clip threshold:

    * kl    — min-KL threshold (cal_kl_threshold)
    * hist  — `percent` quantile of the histogram mass (reference 'hist')
    * mse   — threshold minimizing simulated-quant MSE over the histogram
    * avg   — mean of the per-batch abs-max values
    * abs_max — global abs-max (same as the base observer)
    """

    def __init__(self, algo="kl", bins=2048, percent=0.99999, bit_length=8,
                 name=None):
        super().__init__(bit_length=bit_length)
        if algo not in ("kl", "hist", "mse", "avg", "abs_max"):
            raise ValueError(f"unknown PTQ algo {algo!r}")
        self.algo = algo
        self.bins = int(bins)
        self.percent = float(percent)
        self._hist = np.zeros(self.bins, np.float64)
        self._range = 0.0
        self._batch_maxes: list[float] = []
        self._finalized = False

    def _observe_value(self, xv):
        # histogram calibration needs the full |x| distribution on host
        self._observe(np.abs(np.asarray(xv)).ravel())

    def _observe(self, av):
        cur = float(av.max()) if av.size else 0.0
        self._batch_maxes.append(cur)
        if cur == 0.0:
            return
        if cur > self._range:
            # grow the range: fold existing counts into coarser bins
            if self._range > 0.0:
                factor = int(np.ceil(cur / self._range))
                folded = np.zeros(self.bins, np.float64)
                idx = np.arange(self.bins) // factor
                np.add.at(folded, idx, self._hist)
                self._hist = folded
                self._range *= factor
            else:
                self._range = cur
        h, _ = np.histogram(av, bins=self.bins, range=(0.0, self._range))
        self._hist += h
        # running abs-max keeps fake-quant sane DURING calibration
        self.scale._replace_(
            jnp.asarray(max(float(np.asarray(self.scale._value))
                            if self._seen else 0.0, cur), jnp.float32), None)
        self._seen = True

    # forward comes from the base class; only the observe hook differs

    def finalize(self):
        """Compute the calibrated threshold and write it into `scale`."""
        if self._finalized or not self._batch_maxes:
            return
        bw = self._range / self.bins if self._range else 1.0
        if self.algo == "kl":
            t = cal_kl_threshold(self._hist, bw, self.bit_length)
        elif self.algo == "hist":
            c = np.cumsum(self._hist)
            total = c[-1] if c[-1] > 0 else 1.0
            t = (np.searchsorted(c, self.percent * total) + 1) * bw
        elif self.algo == "mse":
            qmax = 2 ** (self.bit_length - 1) - 1
            centers = (np.arange(self.bins) + 0.5) * bw
            best_t, best_mse = self._range, np.inf
            for i in range(max(1, self.bins // 256), self.bins + 1,
                           max(1, self.bins // 256)):
                t_c = i * bw
                # quantize-with-clip: centers beyond t_c saturate at t_c,
                # so the clipping error is part of the same expression
                q = np.clip(np.round(centers / t_c * qmax), -qmax, qmax) \
                    * t_c / qmax
                mse = float(np.sum(self._hist * (q - centers) ** 2))
                if mse < best_mse:
                    best_mse, best_t = mse, t_c
            t = best_t
        elif self.algo == "avg":
            t = float(np.mean(self._batch_maxes))
        else:                     # abs_max
            t = float(np.max(self._batch_maxes))
        self.scale._replace_(jnp.asarray(max(t, 1e-8), jnp.float32), None)
        self._finalized = True


def _channel_axis(layer):
    from ..nn.layer.common import Linear
    return 1 if isinstance(layer, Linear) else 0   # conv: [out, in, kh, kw]


def _weight_scales(wv, per_channel, axis):
    if not per_channel:
        return jnp.maximum(jnp.max(jnp.abs(wv)), 1e-8)
    red = tuple(d for d in range(wv.ndim) if d != axis)
    s = jnp.maximum(jnp.max(jnp.abs(wv), axis=red), 1e-8)
    shape = [1] * wv.ndim
    shape[axis] = -1
    return s.reshape(shape)


# -- AdaRound (reference slim/quantization/adaround.py): learned weight
# rounding — optimize a per-element soft rounding mask so the QUANTIZED
# layer's outputs match the float layer on calibration data, instead of
# rounding to nearest ---------------------------------------------------------

_ADAROUND_GAMMA, _ADAROUND_ZETA = -0.1, 1.1


def _soft_round(alpha):
    z, g = _ADAROUND_ZETA, _ADAROUND_GAMMA
    return jnp.clip(jax.nn.sigmoid(alpha) * (z - g) + g, 0.0, 1.0)


def adaround_weight(wrapper, inputs, iters=200, reg=0.01, lr=1e-2,
                    warm_start=0.2, beta_range=(20.0, 2.0)):
    """Optimize the rounding of `wrapper.inner.weight` on calibration
    `inputs` (list of Tensors) and return the adarounded weight values.

    Loss = ||layer(x; W_q) - layer(x; W)||^2 + reg * sum(1 - |2h-1|^beta)
    with h the rectified-sigmoid mask, beta annealed high->low and the
    regularizer off during the warm-start fraction (reference
    AdaRoundLoss.compute_round_loss / compute_beta)."""
    import paddle_tpu as paddle

    inner = wrapper.inner
    w = inner.weight._value
    qmax = float(2 ** (wrapper.w_bits - 1) - 1)
    s = _weight_scales(w, wrapper.w_per_channel, _channel_axis(inner)) / qmax
    floor_w = jnp.floor(w / s)
    rest = w / s - floor_w                       # in [0, 1)
    z, g = _ADAROUND_ZETA, _ADAROUND_GAMMA
    # init so _soft_round(alpha) == rest
    p = jnp.clip((rest - g) / (z - g), 1e-4, 1 - 1e-4)
    alpha = Tensor(jnp.log(p / (1 - p)), _internal=True)
    alpha.stop_gradient = False
    from ..optimizer import Adam
    opt = Adam(learning_rate=lr, parameters=[alpha])

    from ..core.autograd import no_grad
    if wrapper.activation_quanter is not None:
        # stashed inputs are PRE-quant; quantize with the FINAL scale
        with no_grad():
            inputs = [wrapper.activation_quanter(x).detach() for x in inputs]
    floor_t = Tensor(floor_w, _internal=True)
    s_t = Tensor(s, _internal=True)
    with no_grad():
        fp_outs = [wrapper._call_with_weight(x, inner.weight).detach()
                   for x in inputs]
    for it in range(iters):
        frac = it / max(iters - 1, 1)
        h = (paddle.nn.functional.sigmoid(alpha) * (z - g) + g).clip(0.0, 1.0)
        wq = (floor_t + h).clip(-qmax, qmax) * s_t
        recon = None
        for x, fp in zip(inputs, fp_outs):
            d = ((wrapper._call_with_weight(x, wq) - fp) ** 2).mean()
            recon = d if recon is None else recon + d
        loss = recon
        if frac >= warm_start:
            b_hi, b_lo = beta_range
            t = (frac - warm_start) / max(1 - warm_start, 1e-9)
            beta = b_lo + 0.5 * (b_hi - b_lo) * (1 + np.cos(t * np.pi))
            round_loss = (1.0 - ((2 * h - 1).abs() ** beta)).sum()
            loss = loss + reg * round_loss
        loss.backward()
        opt.step()
        opt.clear_grad()
    h_final = np.asarray(_soft_round(alpha._value)) >= 0.5
    return jnp.clip(floor_w + h_final, -qmax, qmax) * s
