"""paddle.quantization parity (reference: python/paddle/static/quantization
post-training + QAT passes, and the paddle.quantization QAT config API).

TPU-native scope: simulated int8 quantization.  QAT inserts fake-quant
(quantize-dequantize with a straight-through estimator) on weights and
activations of Linear/Conv2D; PTQ observes abs-max ranges on calibration
batches (observation is independent of train/eval mode).  `convert` bakes
weight quantization onto the int8 grid and freezes the observers — the
quant/dequant ops stay in the inference graph with the calibrated scales,
matching the reference's converted-program shape.  The reference's int8
GEMM kernels (cuDNN/oneDNN) have no public TPU analog, so compute stays in
float with quantized values — the standard simulated-quant formulation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.op import apply_op
from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMaxObserver",
           "quant_dequant", "QuantedLinear", "QuantedConv2D"]


# -- fake quant with straight-through estimator ------------------------------

@jax.custom_vjp
def _fake_quant(x, scale, qmax):
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    return q * scale / qmax


def _fq_fwd(x, scale, qmax):
    return _fake_quant(x, scale, qmax), (x, scale)


def _fq_bwd(res, g):
    x, scale = res
    # STE: pass gradients through inside the clip range, zero outside
    inside = (jnp.abs(x) <= scale).astype(g.dtype)
    return g * inside, jnp.zeros_like(scale), None


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


def quant_dequant(x, scale, bits=8):
    """Simulated quantization op (fake_quantize_dequantize_abs_max)."""
    qmax = float(2 ** (bits - 1) - 1)

    def raw(v, s):
        return _fake_quant(v, jnp.maximum(s, 1e-8), qmax)

    return apply_op(raw, "fake_quantize_dequantize", (x, scale), {})


class FakeQuanterWithAbsMaxObserver(Layer):
    """QAT quanter: tracks a running abs-max and fake-quants through it
    (reference FakeQuanterWithAbsMaxObserverLayer).  Observation is gated by
    `observing`, NOT the train/eval flag, so the standard PTQ flow
    (net.eval() before calibration) still collects statistics; convert()
    freezes it."""

    def __init__(self, moving_rate=0.9, bit_length=8, name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self.register_buffer("scale", Tensor(jnp.ones((), jnp.float32)))
        self._seen = False
        self.observing = True

    def forward(self, x):
        if self.observing:
            if isinstance(x._value, jax.core.Tracer):
                if not self._seen:
                    import warnings
                    warnings.warn(
                        "quant observer ran only under jit: calibration "
                        "needs eager forwards (scale stays at init)")
            else:
                cur = float(jnp.max(jnp.abs(x._value)))
                old = float(np.asarray(self.scale._value))
                new = cur if not self._seen else \
                    self.moving_rate * old + (1 - self.moving_rate) * cur
                self.scale._replace_(jnp.asarray(new, jnp.float32), None)
                self._seen = True
        return quant_dequant(x, self.scale, bits=self.bit_length)


class _QuantedWrapper(Layer):
    """Wraps a Linear/Conv2D: fake-quant activation + weight, then run the
    original layer with the quantized weight."""

    def __init__(self, inner, a_quanter=None, w_bits=8):
        super().__init__()
        self.inner = inner
        self.activation_quanter = a_quanter
        self.w_bits = w_bits

    def _wq(self):
        w = self.inner.weight
        qmax = float(2 ** (self.w_bits - 1) - 1)

        def raw(wv):
            s = jnp.maximum(jnp.max(jnp.abs(wv)), 1e-8)
            return _fake_quant(wv, s, qmax)

        return apply_op(raw, "weight_quantize", (w,), {})

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self._wq()
        return self._call_with_weight(x, w)


class QuantedLinear(_QuantedWrapper):
    def _call_with_weight(self, x, w):
        from ..nn import functional as F
        return F.linear(x, w, self.inner.bias)


class QuantedConv2D(_QuantedWrapper):
    def _call_with_weight(self, x, w):
        from ..nn import functional as F
        i = self.inner
        return F.conv2d(x, w, i.bias, i._stride, i._padding, i._dilation,
                        i._groups, i._data_format)


class QuantConfig:
    """paddle.quantization.QuantConfig parity (subset: global activation /
    weight quanter factories)."""

    def __init__(self, activation=None, weight=None, activation_bits=8,
                 weight_bits=8):
        self.activation = activation
        if weight is not None:
            raise NotImplementedError(
                "custom weight quanters are not supported; weights use "
                "abs-max fake quant at weight_bits precision")
        self.activation_bits = activation_bits
        self.weight_bits = weight_bits

    def add_layer_config(self, *a, **kw):
        pass  # per-layer overrides not needed for the subset

    def _make_act_quanter(self):
        import copy

        if self.activation is None:
            return FakeQuanterWithAbsMaxObserver(
                bit_length=self.activation_bits)
        if isinstance(self.activation, type):
            return self.activation()
        # instance template: each wrapped layer needs its OWN observer
        return copy.deepcopy(self.activation)


def _swap_layers(model, factory):
    """Replace Linear/Conv2D sublayers via `factory(layer)` (in place)."""
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import Conv2D

    for layer in model.sublayers(include_self=True):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, (Linear, Conv2D)) and \
                    not isinstance(sub, _QuantedWrapper):
                layer._sub_layers[name] = factory(sub)
    return model


class QAT:
    """Quantization-aware training driver (reference QAT class)."""

    def __init__(self, config: QuantConfig | None = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=True):
        from ..nn.layer.common import Linear

        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def factory(sub):
            cls = QuantedLinear if isinstance(sub, Linear) else QuantedConv2D
            return cls(sub, self.config._make_act_quanter(),
                       w_bits=self.config.weight_bits)

        return _swap_layers(model, factory)

    def convert(self, model, inplace=True):
        """Freeze for inference: bake weight quantization into the stored
        weights and STOP observing — the quant/dequant ops stay in the graph
        with the calibrated activation scales (reference converted-program
        semantics)."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, FakeQuanterWithAbsMaxObserver):
                layer.observing = False
            if isinstance(layer, _QuantedWrapper):
                qmax = float(2 ** (layer.w_bits - 1) - 1)
                wv = layer.inner.weight._value
                s = jnp.maximum(jnp.max(jnp.abs(wv)), 1e-8)
                layer.inner.weight._replace_(
                    jnp.clip(jnp.round(wv / s * qmax), -qmax, qmax) *
                    s / qmax, None)
        return model


class PTQ(QAT):
    """Post-training quantization: quantize(), run calibration batches (any
    train/eval mode — observers watch until convert), then convert()."""

    # observers are `observing` from construction regardless of train/eval
    # mode, so plain QAT.quantize already yields a calibratable PTQ model
    pass
