"""paddle.sysconfig — parity with python/paddle/sysconfig.py
(get_include:20, get_lib:37): include/lib dirs for building extensions
against this package (paired with utils.cpp_extension)."""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory with the C headers extensions compile against (our
    csrc/ ships paddle_ext.h, the PT_BUILD_OP ABI)."""
    return os.path.join(_ROOT, "csrc")


def get_lib() -> str:
    """Directory holding compiled native libraries (cpp_extension JIT
    outputs land beside the sources)."""
    return os.path.join(_ROOT, "csrc")
