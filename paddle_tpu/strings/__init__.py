"""paddle.strings parity — the phi strings op family
(paddle/phi/api/yaml/strings_ops.yaml: empty, empty_like, lower, upper over
StringTensor; kernels in phi/kernels/strings/, CPU-only in the reference
too).

TPU-native scope: string data never touches the accelerator (same as the
reference — pstring lives on host); the StringTensor here wraps a numpy
unicode array and the ops vectorize via np.char.
"""
from __future__ import annotations

import numpy as np

__all__ = ["StringTensor", "to_string_tensor", "empty", "empty_like",
           "lower", "upper"]


class StringTensor:
    """Host-resident string tensor (phi/core/string_tensor.h analog)."""

    def __init__(self, data):
        self._data = np.asarray(data, dtype=np.str_)

    @property
    def shape(self):
        return tuple(self._data.shape)

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"

    def __eq__(self, other):
        other = other._data if isinstance(other, StringTensor) else other
        return bool(np.array_equal(self._data, np.asarray(other)))


def to_string_tensor(data) -> StringTensor:
    return data if isinstance(data, StringTensor) else StringTensor(data)


def empty(shape, name=None) -> StringTensor:
    return StringTensor(np.full(tuple(shape), "", dtype=np.str_))


def empty_like(x, name=None) -> StringTensor:
    return empty(to_string_tensor(x).shape)


def lower(x, use_utf8_encoding=True, name=None) -> StringTensor:
    """strings_lower kernel: elementwise lowercase (utf8-aware — numpy
    unicode arrays are code-point based, matching the utf8 path)."""
    return StringTensor(np.char.lower(to_string_tensor(x).numpy()))


def upper(x, use_utf8_encoding=True, name=None) -> StringTensor:
    return StringTensor(np.char.upper(to_string_tensor(x).numpy()))
