"""paddle.framework parity: save/load + core re-exports."""
from .io import save, load  # noqa: F401
from ..core.random import seed  # noqa: F401
from ..core.tensor import Tensor  # noqa: F401
from ..core.dtype import get_default_dtype, set_default_dtype  # noqa: F401
