"""Sharded + async checkpointing (SURVEY §5.4: the rebuild's answer to
group-sharded state-dict reassembly and HDFS auto-checkpoint).

Layout: one `.npy` per tensor under the checkpoint dir plus a
`manifest.json` with the key → file/dtype/shape map.  Rationale (TPU-first):
per-tensor files let each axis of a sharded state stream independently and
make partial/streaming restore trivial — the reference's single-pickle
`.pdparams` can't do either.  Async mode snapshots to host numpy first
(device → host copy happens on the caller, cheap on TPU via donation-free
reads), then a writer thread does the IO so the train loop never blocks on
disk.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time

import numpy as np

from ..core.tensor import Tensor

_MANIFEST = "manifest.json"


def _to_numpy_tree(state):
    out = {}
    for k, v in state.items():
        if isinstance(v, Tensor):
            out[k] = v.numpy()
        elif isinstance(v, dict):
            out[k] = _to_numpy_tree(v)
        elif isinstance(v, np.ndarray):
            out[k] = v
        else:
            arr = np.asarray(v)
            # non-numeric leaves (strings, python objects) stay as-is and go
            # into the manifest as JSON
            out[k] = arr if arr.dtype != object else v
    return out


def _flatten(tree, prefix=""):
    flat = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten(v, f"{key}/"))
        else:
            flat[key] = v
    return flat


def _unflatten(flat):
    tree: dict = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def save_sharded(state: dict, dirname: str) -> None:
    """Write `state` (possibly nested state_dict) as per-tensor .npy files +
    manifest.  Atomic: writes into `<dir>.tmp` then renames."""
    from ..observability import trace as _trace
    with _trace.span("checkpoint.save", dir=dirname) as _sp:
        _save_sharded(state, dirname, _sp)


def _save_sharded(state: dict, dirname: str, _sp=None) -> None:
    flat = _flatten(_to_numpy_tree(state))
    if _sp is not None:
        _sp.attrs["leaves"] = len(flat)
        _sp.attrs["bytes"] = int(sum(
            v.nbytes for v in flat.values()
            if isinstance(v, np.ndarray) and v.dtype != object))
    tmp = dirname + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp, exist_ok=True)
    manifest = {}
    scalars = {}
    for i, (key, leaf) in enumerate(flat.items()):
        if isinstance(leaf, np.ndarray) and leaf.dtype != object:
            fname = f"t{i}.npy"
            np.save(os.path.join(tmp, fname), leaf)
            manifest[key] = {"file": fname, "dtype": str(leaf.dtype),
                             "shape": list(leaf.shape)}
        else:
            try:
                json.dumps(leaf)
                scalars[key] = leaf
            except TypeError:
                raise TypeError(
                    f"checkpoint leaf {key!r} of type {type(leaf).__name__} "
                    "is neither a numeric array nor JSON-serializable")
    with open(os.path.join(tmp, _MANIFEST), "w") as f:
        json.dump({"tensors": manifest, "scalars": scalars,
                   "ts": time.time()}, f)
    # crash-safe promote: move the old copy ASIDE first so there is always
    # at least one complete checkpoint on disk, delete it only last
    old = dirname + ".old"
    if os.path.exists(old):
        shutil.rmtree(old)
    if os.path.exists(dirname):
        os.replace(dirname, old)
    os.replace(tmp, dirname)
    if os.path.exists(old):
        shutil.rmtree(old, ignore_errors=True)


def load_sharded(dirname: str, return_numpy: bool = False) -> dict:
    from ..observability import trace as _trace
    with _trace.span("checkpoint.load", dir=dirname) as sp:
        with open(os.path.join(dirname, _MANIFEST)) as f:
            meta_all = json.load(f)
        flat = {}
        for key, meta in meta_all["tensors"].items():
            arr = np.load(os.path.join(dirname, meta["file"]))
            flat[key] = arr if return_numpy else Tensor(arr)
        flat.update(meta_all.get("scalars", {}))
        sp.attrs["leaves"] = len(flat)
        return _unflatten(flat)


class AsyncCheckpointSaver:
    """Non-blocking checkpoint writer: snapshot on the caller, IO in a
    worker thread.  keep_last prunes old step dirs (reference auto_checkpoint
    keeps a bounded history).

    `fs` (fleet.utils.fs client) selects the storage backend: a remote
    client (HDFSClient/GCSClient, `need_upload_download()` True) stages the
    sharded write through a local temp dir then uploads — the reference's
    checkpoint_saver.py + fs.py path (auto_checkpoint.py:636)."""

    def __init__(self, base_dir: str, keep_last: int = 3, fs=None):
        self.base_dir = base_dir
        self.keep_last = keep_last
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None
        self._fs = fs
        self._remote = fs is not None and fs.need_upload_download()
        if self._remote:
            fs.mkdirs(base_dir)
        else:
            os.makedirs(base_dir, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.base_dir, f"step_{step}")

    def save(self, state: dict, step: int, blocking: bool = False):
        from ..observability import trace as _trace
        self.wait()  # one outstanding write at a time
        # snapshot blocks the caller (device → host copies); the write
        # phase runs in the worker thread — two separate spans so a
        # stalled train loop and a stalled disk are distinguishable
        with _trace.span("checkpoint.snapshot", step=step):
            snapshot = _flatten(_to_numpy_tree(state))

        def work():
            try:
                with _trace.span("checkpoint.async_write", step=step,
                                 remote=self._remote):
                    if self._remote:
                        import tempfile
                        with tempfile.TemporaryDirectory() as tmp:
                            local = os.path.join(tmp, f"step_{step}")
                            save_sharded(_unflatten(snapshot), local)
                            self._fs.upload(local, self._step_dir(step))
                    else:
                        save_sharded(_unflatten(snapshot),
                                     self._step_dir(step))
                    self._prune()
            except BaseException as e:  # noqa: BLE001
                self._error = e

        if blocking:
            work()
            self._raise_pending()
        else:
            self._thread = threading.Thread(target=work, daemon=True)
            self._thread.start()

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._raise_pending()

    def _raise_pending(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(f"async checkpoint write failed: {err}")

    def steps(self) -> list[int]:
        if self._remote:
            dirs, _ = self._fs.ls_dir(self.base_dir)
            names = dirs
        else:
            names = os.listdir(self.base_dir)
        out = []
        for name in names:
            if name.startswith("step_") and not name.endswith(".tmp"):
                try:
                    out.append(int(name[len("step_"):]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_step(self):
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(self, step=None, return_numpy=False):
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        if self._remote:
            import tempfile
            with tempfile.TemporaryDirectory() as tmp:
                local = os.path.join(tmp, f"step_{step}")
                self._fs.download(self._step_dir(step), local)
                return load_sharded(local, return_numpy)
        return load_sharded(self._step_dir(step), return_numpy)

    def _prune(self):
        steps = self.steps()
        for s in steps[:-self.keep_last] if self.keep_last else []:
            if self._remote:
                self._fs.delete(self._step_dir(s))
            else:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)
