"""Sharded + async checkpointing with torn-write detection and committed
markers (SURVEY §5.4: the rebuild's answer to group-sharded state-dict
reassembly and HDFS auto-checkpoint; robustness posture per CheckFreq /
Varuna: preemption must cost a resume, not a run).

Layout: one `.npy` per tensor under the checkpoint dir plus a
`manifest.json` with the key → file/dtype/shape/CRC32 map and a
``COMMITTED`` marker file written LAST (after every data file and the
manifest are fsynced) — a directory without the marker is by definition a
torn checkpoint and is never offered for restore.  Rationale (TPU-first):
per-tensor files let each axis of a sharded state stream independently and
make partial/streaming restore trivial — the reference's single-pickle
`.pdparams` can't do either.  Async mode snapshots to host numpy first
(device → host copy happens on the caller, cheap on TPU via donation-free
reads), then a writer thread does the IO so the train loop never blocks on
disk.

Validation: :func:`load_sharded` verifies the marker and every leaf's
CRC32 and raises :class:`CheckpointCorruptError` naming the bad leaf;
:meth:`AsyncCheckpointSaver.restore_latest_valid` walks backward past
corrupt/uncommitted checkpoints, quarantining them (``<dir>.corrupt``)
with a flight-recorder event, so a flipped bit in the newest checkpoint
costs one step of history, never the run.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
import zlib

import numpy as np

from ..core.tensor import Tensor
from ..testing import faults

_MANIFEST = "manifest.json"
_COMMITTED = "COMMITTED"

# metrics registry names (docs/observability.md)
CHECKPOINT_FAILURES_TOTAL = "paddle_tpu_checkpoint_failures_total"
CHECKPOINT_RETRIES_TOTAL = "paddle_tpu_checkpoint_retries_total"

# remote fs retry policy (bounded exponential backoff; docs/robustness.md)
_FS_TRIES = int(os.environ.get("PADDLE_TPU_CHECKPOINT_FS_TRIES", "3"))
_FS_BASE_DELAY = float(os.environ.get(
    "PADDLE_TPU_CHECKPOINT_FS_BASE_DELAY_S", "0.05"))


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed validation (missing COMMITTED marker, missing
    manifest/leaf file, or a CRC32 mismatch)."""

    def __init__(self, msg: str, dirname: str | None = None,
                 leaf: str | None = None):
        super().__init__(msg)
        self.dirname = dirname
        self.leaf = leaf


class ElasticReshardError(RuntimeError):
    """An elastic (cross-topology) restore could not lay a stored leaf out
    on the target mesh — shape not divisible by the requested axes, a spec
    naming an axis the mesh doesn't have, or a source/target state-tree
    mismatch.  The checkpoint itself is NOT corrupt: callers must never
    quarantine or otherwise mutate the checkpoint dir on this error."""

    def __init__(self, msg: str, leaf: str | None = None,
                 spec=None, mesh_axes: dict | None = None):
        super().__init__(msg)
        self.leaf = leaf
        self.spec = spec
        self.mesh_axes = dict(mesh_axes or {})


class ElasticResumeError(RuntimeError):
    """A world-size-aware resume could not map the checkpoint's global
    sample offset onto the new topology (offset not divisible by the new
    global batch).  The checkpoint is intact — pick a compatible
    batch-size x dp-world product, or resume on the original topology."""

    def __init__(self, msg: str, samples: int | None = None,
                 global_batch_size: int | None = None):
        super().__init__(msg)
        self.samples = samples
        self.global_batch_size = global_batch_size


def mesh_axes_of(mesh) -> dict:
    """``{axis_name: size}`` of a Mesh — the topology fingerprint stored
    in train-state checkpoints and quoted by elastic-restore errors."""
    if mesh is None:
        return {}
    return {str(a): int(mesh.shape[a]) for a in mesh.axis_names}


def _to_numpy_tree(state):
    out = {}
    for k, v in state.items():
        if isinstance(v, Tensor):
            out[k] = v.numpy()
        elif isinstance(v, dict):
            out[k] = _to_numpy_tree(v)
        elif isinstance(v, np.ndarray):
            out[k] = v
        else:
            arr = np.asarray(v)
            # non-numeric leaves (strings, python objects) stay as-is and go
            # into the manifest as JSON
            out[k] = arr if arr.dtype.kind not in "USO" else v
    return out


def _flatten(tree, prefix=""):
    flat = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten(v, f"{key}/"))
        else:
            flat[key] = v
    return flat


def _unflatten(flat):
    tree: dict = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def _crc32(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _fsync_file(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without O_RDONLY dirs
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def is_committed(dirname: str) -> bool:
    """True when `dirname` holds a fully written checkpoint (marker file
    present — written last, so its existence implies the rest)."""
    return os.path.isfile(os.path.join(dirname, _COMMITTED))


def save_sharded(state: dict, dirname: str) -> None:
    """Write `state` (possibly nested state_dict) as per-tensor .npy files
    + manifest + COMMITTED marker.  Atomic: writes into `<dir>.tmp`
    (fsyncing every file and the marker) then renames."""
    from ..observability import trace as _trace
    with _trace.span("checkpoint.save", dir=dirname) as _sp:
        _save_sharded(state, dirname, _sp)


def _save_sharded(state: dict, dirname: str, _sp=None) -> None:
    flat = _flatten(_to_numpy_tree(state))
    if _sp is not None:
        _sp.attrs["leaves"] = len(flat)
        _sp.attrs["bytes"] = int(sum(
            v.nbytes for v in flat.values()
            if isinstance(v, np.ndarray) and v.dtype != object))
    tmp = dirname + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp, exist_ok=True)
    manifest = {}
    scalars = {}
    for i, (key, leaf) in enumerate(flat.items()):
        if isinstance(leaf, np.ndarray) and leaf.dtype != object:
            fname = f"t{i}.npy"
            fpath = os.path.join(tmp, fname)
            np.save(fpath, leaf)
            faults.fault_point("checkpoint.write", path=fpath, leaf=key)
            _fsync_file(fpath)
            manifest[key] = {"file": fname, "dtype": str(leaf.dtype),
                             "shape": list(leaf.shape),
                             "crc32": _crc32(leaf)}
        else:
            try:
                json.dumps(leaf)
                scalars[key] = leaf
            except TypeError:
                raise TypeError(
                    f"checkpoint leaf {key!r} of type {type(leaf).__name__} "
                    "is neither a numeric array nor JSON-serializable")
    mpath = os.path.join(tmp, _MANIFEST)
    with open(mpath, "w") as f:
        json.dump({"tensors": manifest, "scalars": scalars,
                   "ts": time.time(), "format": 2}, f)
    faults.fault_point("checkpoint.manifest", path=mpath)
    _fsync_file(mpath)
    # the commit point: the marker is written LAST and fsynced before the
    # atomic rename — a crash anywhere above leaves a marker-less dir that
    # validation treats as torn
    faults.fault_point("checkpoint.commit")
    cpath = os.path.join(tmp, _COMMITTED)
    with open(cpath, "w") as f:
        json.dump({"ts": time.time(), "leaves": len(flat)}, f)
    _fsync_file(cpath)
    _fsync_dir(tmp)
    # crash-safe promote: move the old copy ASIDE first so there is always
    # at least one complete checkpoint on disk, delete it only last
    faults.fault_point("checkpoint.promote")
    old = dirname + ".old"
    if os.path.exists(old):
        shutil.rmtree(old)
    if os.path.exists(dirname):
        os.replace(dirname, old)
    os.replace(tmp, dirname)
    _fsync_dir(os.path.dirname(os.path.abspath(dirname)))
    if os.path.exists(old):
        shutil.rmtree(old, ignore_errors=True)


def _validate_reshard_spec(key, shape, spec, mesh):
    """Raise :class:`ElasticReshardError` when `spec` cannot lay an array
    of `shape` out over `mesh` — the typed error names the leaf AND the
    leaf/mesh mismatch so a mis-targeted elastic restore is diagnosable
    without reading shard dumps."""
    axes = mesh_axes_of(mesh)
    entries = list(spec) if spec is not None else []
    if len(entries) > len(shape):
        raise ElasticReshardError(
            f"elastic restore: leaf {key!r} of shape {tuple(shape)} got "
            f"spec {spec} with more entries than dims", leaf=key, spec=spec,
            mesh_axes=axes)
    for dim, entry in enumerate(entries):
        names = entry if isinstance(entry, tuple) else (entry,)
        factor = 1
        for name in names:
            if name is None:
                continue
            if name not in axes:
                raise ElasticReshardError(
                    f"elastic restore: leaf {key!r} spec {spec} names mesh "
                    f"axis {name!r} but the target mesh only has "
                    f"{axes}", leaf=key, spec=spec, mesh_axes=axes)
            factor *= axes[name]
        if factor > 1 and shape[dim] % factor:
            raise ElasticReshardError(
                f"elastic restore: leaf {key!r} dim {dim} of size "
                f"{shape[dim]} is not divisible by mesh axes "
                f"{[n for n in names if n]} (x{factor}) on target mesh "
                f"{axes}", leaf=key, spec=spec, mesh_axes=axes)


def _relayout(key, arr, spec, mesh):
    """Host array -> device array laid out as `spec` over `mesh` (the
    host-side gather/reslice of an elastic restore: stored bytes are the
    GLOBAL array, so any target layout is a pure placement)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..distributed import mesh as mesh_mod
    spec = spec if spec is not None else PartitionSpec()
    _validate_reshard_spec(key, arr.shape, spec, mesh)
    faults.fault_point("restore.relayout", leaf=key)
    return mesh_mod.put_global(arr, NamedSharding(mesh, spec))


def load_sharded(dirname: str, return_numpy: bool = False,
                 verify: bool = True, target_mesh=None,
                 target_specs=None) -> dict:
    """Load a sharded checkpoint; with `verify` (default) requires the
    COMMITTED marker and checks every leaf's CRC32, raising
    :class:`CheckpointCorruptError` naming the offending leaf.

    Elastic path: with `target_mesh`, every array leaf is re-laid-out onto
    that mesh after validation — `target_specs` maps flattened keys (e.g.
    ``"params/linear_0.w_0"``) to PartitionSpecs (or is a callable
    ``(key, shape) -> spec``); unmapped leaves are replicated.  CRC
    verification always runs on the STORED bytes before any relayout, and
    a relayout failure (:class:`ElasticReshardError`) leaves the
    checkpoint dir untouched."""
    from ..observability import trace as _trace
    if target_mesh is not None and return_numpy:
        raise ValueError("return_numpy=True and target_mesh are exclusive "
                         "(a relayout result is a device array)")
    with _trace.span("checkpoint.load", dir=dirname,
                     elastic=target_mesh is not None) as sp:
        mpath = os.path.join(dirname, _MANIFEST)
        if not os.path.isfile(mpath):
            raise CheckpointCorruptError(
                f"checkpoint {dirname!r} has no manifest", dirname=dirname)
        if verify and not is_committed(dirname):
            raise CheckpointCorruptError(
                f"checkpoint {dirname!r} has no COMMITTED marker "
                "(torn or in-flight write)", dirname=dirname)
        try:
            with open(mpath) as f:
                meta_all = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            raise CheckpointCorruptError(
                f"checkpoint {dirname!r} manifest unreadable: {e}",
                dirname=dirname)
        # phase 1 — read + CRC-verify every leaf from the stored bytes
        arrays = {}
        for key, meta in meta_all["tensors"].items():
            fpath = os.path.join(dirname, meta["file"])
            faults.fault_point("restore.read", path=fpath, leaf=key)
            try:
                arr = np.load(fpath)
            except (OSError, ValueError, EOFError) as e:
                raise CheckpointCorruptError(
                    f"checkpoint leaf {key!r} unreadable "
                    f"({meta['file']}): {e}", dirname=dirname, leaf=key)
            if verify and "crc32" in meta and _crc32(arr) != meta["crc32"]:
                raise CheckpointCorruptError(
                    f"checkpoint leaf {key!r} failed CRC32 validation "
                    f"({meta['file']})", dirname=dirname, leaf=key)
            arrays[key] = arr
        # phase 2 — optional relayout onto the target mesh (validation
        # first for every leaf, so a mismatch raises before any device
        # placement happens)
        flat = {}
        if target_mesh is not None:
            if callable(target_specs):
                spec_of = target_specs
            else:
                specs = dict(target_specs or {})
                spec_of = lambda key, shape: specs.get(key)  # noqa: E731
            for key, arr in arrays.items():
                flat[key] = Tensor(
                    _relayout(key, arr, spec_of(key, arr.shape),
                              target_mesh), _internal=True)
        else:
            for key, arr in arrays.items():
                flat[key] = arr if return_numpy else Tensor(arr)
        flat.update(meta_all.get("scalars", {}))
        sp.attrs["leaves"] = len(flat)
        return _unflatten(flat)


class AsyncCheckpointSaver:
    """Non-blocking checkpoint writer: snapshot on the caller, IO in a
    worker thread.  keep_last prunes old step dirs (reference auto_checkpoint
    keeps a bounded history).

    `fs` (fleet.utils.fs client) selects the storage backend: a remote
    client (HDFSClient/GCSClient, `need_upload_download()` True) stages the
    sharded write through a local temp dir then uploads — the reference's
    checkpoint_saver.py + fs.py path (auto_checkpoint.py:636).  Remote
    uploads go payload-first, COMMITTED marker last (each under the
    bounded-backoff retry policy), so an interrupted upload is a
    marker-less remote dir that ``steps()`` never counts — not a checkpoint
    that restores garbage."""

    def __init__(self, base_dir: str, keep_last: int = 3, fs=None):
        self.base_dir = base_dir
        self.keep_last = keep_last
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None
        self._fs = fs
        self._remote = fs is not None and fs.need_upload_download()
        if self._remote:
            fs.mkdirs(base_dir)
        else:
            os.makedirs(base_dir, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.base_dir, f"step_{step}")

    def _retry(self, fn, *args, name: str):
        from ..utils.retry import retry_call

        def call():
            faults.fault_point(name)  # fs.upload / fs.download
            return fn(*args)
        return retry_call(call, name=name, tries=_FS_TRIES,
                          base_delay=_FS_BASE_DELAY,
                          counter=CHECKPOINT_RETRIES_TOTAL)

    def _upload_committed(self, local: str, remote: str):
        """Payload first, marker last: the remote dir only becomes a
        checkpoint once everything else arrived."""
        marker = os.path.join(local, _COMMITTED)
        marker_aside = local + "." + _COMMITTED
        os.replace(marker, marker_aside)
        faults.fault_point("checkpoint.upload", dir=remote)
        self._retry(self._fs.upload, local, remote, name="fs.upload")
        faults.fault_point("checkpoint.upload_commit", dir=remote)
        self._retry(self._fs.upload, marker_aside,
                    remote + "/" + _COMMITTED, name="fs.upload")

    def _note_failure(self, err: BaseException, step, phase: str):
        """Emit the failure signal AT failure time (the caller may not
        call wait() for many steps)."""
        from ..observability import flight, registry
        flight.record("checkpoint", "write_failed", step=int(step),
                      phase=phase, error=f"{type(err).__name__}: {err}"[:300])
        registry().counter(
            CHECKPOINT_FAILURES_TOTAL,
            "checkpoint writes/restores that failed").inc(
            1.0, labels={"phase": phase})

    def save(self, state: dict, step: int, blocking: bool = False):
        from ..observability import trace as _trace
        self.wait()  # one outstanding write at a time
        # snapshot blocks the caller (device → host copies); the write
        # phase runs in the worker thread — two separate spans so a
        # stalled train loop and a stalled disk are distinguishable
        with _trace.span("checkpoint.snapshot", step=step):
            snapshot = _flatten(_to_numpy_tree(state))

        def work():
            try:
                with _trace.span("checkpoint.async_write", step=step,
                                 remote=self._remote):
                    if self._remote:
                        import tempfile
                        with tempfile.TemporaryDirectory() as tmp:
                            local = os.path.join(tmp, f"step_{step}")
                            save_sharded(_unflatten(snapshot), local)
                            self._upload_committed(local,
                                                   self._step_dir(step))
                    else:
                        save_sharded(_unflatten(snapshot),
                                     self._step_dir(step))
                    self._prune()
            except BaseException as e:  # noqa: BLE001
                self._note_failure(e, step, "async_write")
                self._error = e

        if blocking:
            work()
            self._raise_pending()
        else:
            self._thread = threading.Thread(target=work, daemon=True)
            self._thread.start()

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._raise_pending()

    def _raise_pending(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(f"async checkpoint write failed: {err}")

    def _is_committed_step(self, name: str) -> bool:
        if self._remote:
            return self._fs.is_file(
                os.path.join(self.base_dir, name, _COMMITTED))
        return is_committed(os.path.join(self.base_dir, name))

    def steps(self) -> list[int]:
        """Committed steps only: a dir without the COMMITTED marker is a
        torn write (or an in-flight upload), never a restore candidate."""
        if self._remote:
            dirs, _ = self._fs.ls_dir(self.base_dir)
            names = dirs
        else:
            names = os.listdir(self.base_dir)
        out = []
        for name in names:
            if name.startswith("step_") and not name.endswith(".tmp"):
                try:
                    step = int(name[len("step_"):])
                except ValueError:
                    continue
                if self._is_committed_step(name):
                    out.append(step)
        return sorted(out)

    def latest_step(self):
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(self, step=None, return_numpy=False, target_mesh=None,
                target_specs=None):
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        if self._remote:
            import tempfile
            with tempfile.TemporaryDirectory() as tmp:
                local = os.path.join(tmp, f"step_{step}")
                self._retry(self._fs.download, self._step_dir(step), local,
                            name="fs.download")
                return load_sharded(local, return_numpy,
                                    target_mesh=target_mesh,
                                    target_specs=target_specs)
        return load_sharded(self._step_dir(step), return_numpy,
                            target_mesh=target_mesh,
                            target_specs=target_specs)

    def restore_latest_valid(self, return_numpy=False, target_mesh=None,
                             target_specs=None):
        """Walk backward from the newest committed step past anything that
        fails validation, quarantining bad dirs (``<dir>.corrupt``) with a
        flight event.  Returns ``(step, state)`` or ``(None, None)`` when
        no valid checkpoint exists.

        Elastic failures are different: an :class:`ElasticReshardError`
        (or an injected restore fault) means the CHECKPOINT is fine and
        the restore request is wrong — it re-raises immediately and never
        quarantines, so a failed elastic restore leaves the checkpoint dir
        untouched."""
        from ..observability import flight, registry
        for step in reversed(self.steps()):
            try:
                return step, self.restore(step, return_numpy,
                                          target_mesh=target_mesh,
                                          target_specs=target_specs)
            except (ElasticReshardError, faults.FaultInjected):
                raise  # not a corrupt dir: never quarantine
            except Exception as e:  # noqa: BLE001 — any broken dir: skip it
                flight.record("checkpoint", "quarantine", step=int(step),
                              dir=self._step_dir(step),
                              error=f"{type(e).__name__}: {e}"[:300])
                registry().counter(
                    CHECKPOINT_FAILURES_TOTAL,
                    "checkpoint writes/restores that failed").inc(
                    1.0, labels={"phase": "restore"})
                self._quarantine(step)
        return None, None

    def _quarantine(self, step: int):
        src = self._step_dir(step)
        dst = src + ".corrupt"
        try:
            if self._remote:
                if self._fs.is_exist(dst):
                    self._fs.delete(dst)
                self._fs.mv(src, dst)
            else:
                if os.path.exists(dst):
                    shutil.rmtree(dst, ignore_errors=True)
                os.replace(src, dst)
        except OSError:
            pass  # quarantine is best-effort; steps() already skips it

    def _prune(self):
        steps = self.steps()
        for s in steps[:-self.keep_last] if self.keep_last else []:
            if self._remote:
                self._fs.delete(self._step_dir(s))
            else:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)
        self._sweep_orphans()

    def _sweep_orphans(self):
        """Remove debris a crashed writer leaves behind: `step_*.tmp`
        partial writes, `*.old` promote leftovers, and marker-less step
        dirs older than the newest committed step (interrupted uploads)."""
        from ..observability import flight
        newest = max(self.steps(), default=None)
        if self._remote:
            dirs, _ = self._fs.ls_dir(self.base_dir)
        else:
            dirs = [n for n in os.listdir(self.base_dir)
                    if os.path.isdir(os.path.join(self.base_dir, n))]
        for name in dirs:
            full = os.path.join(self.base_dir, name)
            orphan = name.endswith(".tmp") or name.endswith(".old")
            if not orphan and name.startswith("step_") and \
                    newest is not None and not name.endswith(".corrupt"):
                try:
                    orphan = int(name[len("step_"):]) < newest and \
                        not self._is_committed_step(name)
                except ValueError:
                    orphan = False
            if orphan:
                flight.record("checkpoint", "sweep_orphan", dir=full)
                if self._remote:
                    self._fs.delete(full)
                else:
                    shutil.rmtree(full, ignore_errors=True)
