"""Object save/load (reference: python/paddle/framework/io.py:574,791).

File contract preserved: ``paddle.save(layer.state_dict(), "model.pdparams")``
pickles a nest of numpy arrays; ``paddle.load`` returns Tensors.  Checkpoints
written by this framework are plain pickles of numpy data — portable across
hosts and readable without JAX.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return _SavedTensor(obj.numpy())
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


class _SavedTensor:
    """Marker wrapper so load() can distinguish tensors from raw ndarrays."""

    __slots__ = ("array",)

    def __init__(self, array: np.ndarray):
        self.array = array


def _from_saved(obj, return_numpy=False):
    if isinstance(obj, _SavedTensor):
        return obj.array if return_numpy else Tensor(obj.array)
    if isinstance(obj, np.ndarray):
        return obj if return_numpy else Tensor(obj)
    if isinstance(obj, dict):
        return {k: _from_saved(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saved(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saved(obj, return_numpy=return_numpy)
