"""Preemption handling — turn SIGTERM into a checkpoint, not a lost run.

TPU fleets preempt routinely (Varuna's whole premise is training on spot
capacity); the scheduler's kill arrives as SIGTERM with a grace window.
This module converts the first such signal into a *request*: a flag the
train loops (``ShardedTrainStep.__call__/run_steps`` and the hapi fit
loop via ``CheckpointCallback``) poll at step boundaries to write an
emergency checkpoint and stop cleanly.  A second delivery of the same
signal escalates — handlers are uninstalled and the signal is re-raised,
so the PR 2 watchdog chain (flight-tail crash dump, then the default
disposition) still runs for an impatient scheduler.

Layering with the watchdog: :func:`install` *wraps* whatever handler is
current (including the watchdog's dump-then-die handler) instead of
replacing it blindly; :func:`uninstall` restores it.  The first signal is
swallowed on purpose — dying immediately is exactly what this module
exists to avoid — the previous chain runs on escalation or after
uninstall.

Programmatic use (tests, cooperative schedulers)::

    preemption.request()          # same effect as one SIGTERM
    if preemption.requested(): ...
"""
from __future__ import annotations

import contextlib
import os
import signal
import threading

from ..observability import flight

__all__ = ["TrainingPreempted", "install", "uninstall", "guard",
           "request", "requested", "clear", "mark_saved", "last_saved_step"]


class TrainingPreempted(RuntimeError):
    """Raised by a train step after the emergency checkpoint is on disk:
    the run was preempted and should exit so the scheduler can reschedule;
    ``step`` is the checkpointed optimizer step to resume from."""

    def __init__(self, step: int | None = None, msg: str | None = None):
        super().__init__(
            msg or f"training preempted; emergency checkpoint at step {step}")
        self.step = step


_requested = threading.Event()
_lock = threading.Lock()
_prev: dict[int, object] = {}
_last_saved_step: int | None = None


def requested() -> bool:
    return _requested.is_set()


def request(reason: str = "api"):
    """Arm the preemption flag (what the signal handler does)."""
    if not _requested.is_set():
        _requested.set()
        flight.record("preemption", "requested", reason=reason)


def clear():
    global _last_saved_step
    _requested.clear()
    _last_saved_step = None


def mark_saved(step: int, topology: dict | None = None):
    """Train loops call this right after the emergency checkpoint commits
    (flight event + bookkeeping for tests/operators).  `topology` is the
    writer's mesh axes (``{"dp": 2, "mp": 4}``) — recorded so a resume on
    a DIFFERENT mesh (elastic restart) can be traced back to the topology
    that wrote the emergency checkpoint."""
    global _last_saved_step
    _last_saved_step = int(step)
    attrs = {"step": int(step)}
    if topology:
        attrs["topology"] = str(topology)
    flight.record("preemption", "emergency_checkpoint", **attrs)


def last_saved_step() -> int | None:
    return _last_saved_step


def _handler(sig, frame):
    if _requested.is_set():
        # second delivery: the grace period is over — restore the previous
        # chain (watchdog dump → default disposition) and re-deliver
        uninstall()
        os.kill(os.getpid(), sig)
        return
    request(reason=f"signal_{signal.Signals(sig).name}")


def installed() -> bool:
    return bool(_prev)


def install(signals=(signal.SIGTERM, signal.SIGINT)) -> bool:
    """Wrap the current handlers (idempotent).  Returns False when signal
    installation is impossible (non-main thread) — training still works,
    preemption can only arrive via :func:`request`."""
    with _lock:
        ok = True
        for sig in signals:
            if sig in _prev:
                continue
            try:
                cur = signal.getsignal(sig)
                signal.signal(sig, _handler)
                _prev[sig] = cur
            except (ValueError, OSError):  # not main thread
                ok = False
        return ok


def uninstall():
    with _lock:
        for sig, prev in list(_prev.items()):
            try:
                if signal.getsignal(sig) is _handler:
                    signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
            _prev.pop(sig, None)


@contextlib.contextmanager
def guard(signals=(signal.SIGTERM, signal.SIGINT)):
    """Install for the scope of a train loop, restore after."""
    install(signals)
    try:
        yield
    finally:
        uninstall()
