"""paddle.cost_model — per-op cost data API (reference:
python/paddle/cost_model/cost_model.py: CostModel.profile_measure:46,
static_cost_data:63, get_static_op_time:72 over a bundled
static_op_benchmark.json of CI-measured op times).

TPU-native: the static table is measured on THIS device class by
tools/op_bench.py (`python tools/op_bench.py --output
paddle_tpu/cost_model/static_op_benchmark.json`); profile_measure runs a
jitted callable and returns real device time from the xplane trace — the
same timing source the perf work trusts (docs/PERF.md)."""
from __future__ import annotations

import json
import os

__all__ = ["CostModel"]


class CostModel:
    def __init__(self):
        self._static_cost_data = None

    def build_program(self):
        """Reference demo analog: a tiny static Program (fc + mean +
        SGD) as (startup, main) — runnable via profile_measure."""
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        paddle.seed(0)
        model = nn.Sequential(nn.Linear(1, 10))
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=model.parameters())

        def main(x):
            loss = model(paddle.to_tensor(x)).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        x = np.random.random(size=(10, 1)).astype("float32")
        return (lambda: None), (lambda: main(x))

    def profile_measure(self, startup_program=None, main_program=None,
                        device="tpu", fetch_cost_list=("time",)):
        """Run the program once warm and report measured cost.  Returns
        {"time": seconds} (+ device kind) — the reference returns the
        C++ CostModel's ProfileMeasure dict."""
        import time as _time

        import jax

        if startup_program is not None:
            startup_program()
        main = main_program if main_program is not None else \
            self.build_program()[1]
        out = main()   # warm (compile)
        leaf = getattr(out, "_value", out)
        try:
            jax.block_until_ready(leaf)
        except Exception:
            pass
        t0 = _time.perf_counter()
        out = main()
        leaf = getattr(out, "_value", out)
        try:
            jax.block_until_ready(leaf)
        except Exception:
            pass
        dt = _time.perf_counter() - t0
        dev = jax.devices()[0]
        return {"time": dt,
                "device": getattr(dev, "device_kind", str(dev))}

    def static_cost_data(self):
        path = os.path.join(os.path.dirname(__file__),
                            "static_op_benchmark.json")
        with open(path) as f:
            self._static_cost_data = json.load(f)
        return self._static_cost_data

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        """Look up an op's measured time (reference cost_model.py:72 —
        same row schema: op/config/speed fields)."""
        if op_name is None:
            raise ValueError(
                "op_name should not be empty when you want to get static "
                "op time")
        if self._static_cost_data is None:
            self.static_cost_data()
        op_cost = {}
        for op_data in self._static_cost_data:
            cfg = op_data.get("config", "")
            # dtype filter applies only when the config names a dtype
            dtype_ok = dtype in cfg or not any(
                d in cfg for d in ("float", "int", "bfloat"))
            if op_data["op"] == op_name and dtype_ok:
                key = "speed_us" if forward else "speed_us_backward"
                op_cost["op_time"] = op_data.get(
                    key, op_data.get("speed_us"))
                op_cost["config"] = cfg
        return op_cost
