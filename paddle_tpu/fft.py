"""paddle.fft parity (python/paddle/fft.py — the pocketfft-backed op family;
here jnp.fft, which XLA lowers to the TPU FFT custom-call)."""
from __future__ import annotations

import jax.numpy as jnp

from .core.op import apply_op
from .core.tensor import Tensor

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2", "hfft2", "ihfft2", "hfftn", "ihfftn",
           "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _norm(norm):
    return None if norm in (None, "backward") else norm


def _wrap1(jfn, op_name):
    def fn(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(lambda v: jfn(v, n=n, axis=axis, norm=_norm(norm)),
                        op_name, (x,), {})
    fn.__name__ = op_name
    return fn


def _wrap2(jfn, op_name):
    def fn(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op(lambda v: jfn(v, s=s, axes=axes, norm=_norm(norm)),
                        op_name, (x,), {})
    fn.__name__ = op_name
    return fn


def _wrapn(jfn, op_name):
    def fn(x, s=None, axes=None, norm="backward", name=None):
        return apply_op(lambda v: jfn(v, s=s, axes=axes, norm=_norm(norm)),
                        op_name, (x,), {})
    fn.__name__ = op_name
    return fn


fft = _wrap1(jnp.fft.fft, "fft")
ifft = _wrap1(jnp.fft.ifft, "ifft")
rfft = _wrap1(jnp.fft.rfft, "rfft")
irfft = _wrap1(jnp.fft.irfft, "irfft")
hfft = _wrap1(jnp.fft.hfft, "hfft")
ihfft = _wrap1(jnp.fft.ihfft, "ihfft")
fft2 = _wrap2(jnp.fft.fft2, "fft2")
ifft2 = _wrap2(jnp.fft.ifft2, "ifft2")
rfft2 = _wrap2(jnp.fft.rfft2, "rfft2")
irfft2 = _wrap2(jnp.fft.irfft2, "irfft2")
# hfft2/hfftn compose hermitian fft over the last axis with fft over the
# rest (the reference kernels do the same decomposition)
hfft2 = _wrap2(lambda a, s=None, axes=(-2, -1), norm=None:
               jnp.fft.fft(jnp.fft.hfft(a, n=None if s is None else s[-1],
                                        axis=axes[-1], norm=norm),
                           axis=axes[0], norm=norm), "hfft2")
ihfft2 = _wrap2(lambda a, s=None, axes=(-2, -1), norm=None:
                jnp.fft.ihfft(jnp.fft.ifft(a, axis=axes[0], norm=norm),
                              n=None if s is None else s[-1],
                              axis=axes[-1], norm=norm), "ihfft2")
def _hfftn_impl(a, s=None, axes=None, norm=None):
    import jax.numpy as _jnp
    ax = tuple(range(a.ndim)) if axes is None else tuple(axes)
    out = _jnp.fft.hfft(a, n=None if s is None else s[-1], axis=ax[-1],
                        norm=norm)
    for d in ax[:-1][::-1]:
        out = _jnp.fft.fft(out, axis=d, norm=norm)
    return out


def _ihfftn_impl(a, s=None, axes=None, norm=None):
    import jax.numpy as _jnp
    ax = tuple(range(a.ndim)) if axes is None else tuple(axes)
    out = a
    for d in ax[:-1]:
        out = _jnp.fft.ifft(out, axis=d, norm=norm)
    return _jnp.fft.ihfft(out, n=None if s is None else s[-1],
                          axis=ax[-1], norm=norm)


hfftn = _wrapn(_hfftn_impl, "hfftn")
ihfftn = _wrapn(_ihfftn_impl, "ihfftn")
fftn = _wrapn(jnp.fft.fftn, "fftn")
ifftn = _wrapn(jnp.fft.ifftn, "ifftn")
rfftn = _wrapn(jnp.fft.rfftn, "rfftn")
irfftn = _wrapn(jnp.fft.irfftn, "irfftn")


def fftfreq(n, d=1.0, dtype="float32", name=None):
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype), _internal=True)


def rfftfreq(n, d=1.0, dtype="float32", name=None):
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype), _internal=True)


def fftshift(x, axes=None, name=None):
    return apply_op(lambda v: jnp.fft.fftshift(v, axes=axes), "fftshift",
                    (x,), {})


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda v: jnp.fft.ifftshift(v, axes=axes), "ifftshift",
                    (x,), {})

from .ops.compat_surface import is_complex  # noqa: E402,F401
