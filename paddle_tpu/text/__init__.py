"""paddle.text parity (python/paddle/text/): viterbi decoding for sequence
labeling (ViterbiDecoder at text/viterbi_decode.py:93, backed by the
viterbi_decode op)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Layer

from .datasets import (Conll05st, Imdb, Imikolov,  # noqa: F401
                       Movielens, UCIHousing, WMT14, WMT16)

__all__ = ["viterbi_decode", "ViterbiDecoder", "Imdb", "Imikolov",
           "UCIHousing", "Movielens", "Conll05st", "WMT14", "WMT16"]


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """viterbi_decode op parity: returns (scores, best_paths).

    potentials: [B, T, N] emission scores; transition_params: [N, N] (when
    include_bos_eos_tag, row N-1 is the start/BOS transition and row N-2
    the stop/EOS transition, matching the reference kernel's row split);
    lengths: [B] int actual lengths.

    Delegates to the registered viterbi_decode op (ops/extended.py) — the
    single implementation of the decode recurrence.
    """
    from ..ops.extended import viterbi_decode as _op

    pot = potentials if isinstance(potentials, Tensor) else \
        Tensor(jnp.asarray(potentials), _internal=True)
    trans = transition_params if isinstance(transition_params, Tensor) else \
        Tensor(jnp.asarray(transition_params), _internal=True)
    if lengths is not None and not isinstance(lengths, Tensor):
        lengths = Tensor(jnp.asarray(lengths), _internal=True)
    scores, paths = _op(pot, trans, lengths=lengths,
                        include_bos_eos_tag=include_bos_eos_tag)
    paths.stop_gradient = True
    return scores, paths


class ViterbiDecoder(Layer):
    """text/viterbi_decode.py:93 parity."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)

from . import datasets  # noqa: F401  (Imdb/Imikolov/UCIHousing/Movielens)
