"""paddle.text parity (python/paddle/text/): viterbi decoding for sequence
labeling (ViterbiDecoder at text/viterbi_decode.py:93, backed by the
viterbi_decode op)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.op import apply_op
from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """viterbi_decode op parity: returns (scores, best_paths).

    potentials: [B, T, N] emission scores; transition_params: [N, N] (with
    BOS=N-2/EOS=N-1 rows/cols when include_bos_eos_tag, matching the
    reference convention); lengths: [B] int actual lengths.
    """

    def raw(pot, trans, lens):
        b, t, n = pot.shape
        if lens is None:
            lens = jnp.full((b,), t, jnp.int32)
        if include_bos_eos_tag:
            bos, eos = n - 2, n - 1
            init = pot[:, 0] + trans[bos][None, :]
        else:
            init = pot[:, 0]

        def step(carry, xs):
            alpha, idx = carry, xs["i"]
            emit = xs["emit"]  # [B, N]
            scores = alpha[:, :, None] + trans[None, :, :] + \
                emit[:, None, :]
            best_prev = scores.argmax(axis=1)  # [B, N]
            new_alpha = scores.max(axis=1)
            # positions beyond a sequence's length keep their alpha frozen
            active = (idx < lens)[:, None]
            new_alpha = jnp.where(active, new_alpha, alpha)
            best_prev = jnp.where(active, best_prev,
                                  jnp.arange(n)[None, :])
            return new_alpha, best_prev

        xs = {"emit": jnp.moveaxis(pot[:, 1:], 1, 0),
              "i": jnp.arange(1, t)}
        alpha, backptrs = jax.lax.scan(step, init, xs)
        if include_bos_eos_tag:
            alpha = alpha + trans[:, eos][None, :]
        scores = alpha.max(axis=1)
        last_tag = alpha.argmax(axis=1)  # [B]

        def backward(carry, bp):
            # carry = tag at step i+1; emit tag_i = bp[tag_{i+1}]
            prev = jnp.take_along_axis(bp, carry[:, None], axis=1)[:, 0]
            return prev, prev

        _, path_rev = jax.lax.scan(backward, last_tag, backptrs,
                                   reverse=True)
        paths = jnp.concatenate([jnp.moveaxis(path_rev, 0, 1),
                                 last_tag[:, None]], axis=1)  # [B, T]
        return scores, paths.astype(jnp.int64)

    pot = potentials if isinstance(potentials, Tensor) else \
        Tensor(jnp.asarray(potentials), _internal=True)
    trans = transition_params if isinstance(transition_params, Tensor) else \
        Tensor(jnp.asarray(transition_params), _internal=True)
    if lengths is None:
        scores, paths = apply_op(lambda p, tr: raw(p, tr, None),
                                 "viterbi_decode", (pot, trans), {})
    else:
        lens = lengths if isinstance(lengths, Tensor) else \
            Tensor(jnp.asarray(lengths), _internal=True)
        scores, paths = apply_op(raw, "viterbi_decode", (pot, trans, lens),
                                 {})
    paths.stop_gradient = True
    return scores, paths


class ViterbiDecoder(Layer):
    """text/viterbi_decode.py:93 parity."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)

from . import datasets  # noqa: F401  (Imdb/Imikolov/UCIHousing/Movielens)
