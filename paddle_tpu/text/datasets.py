"""paddle.text.datasets parity — Imdb, Imikolov, UCIHousing, Movielens.

Reference: python/paddle/text/datasets/{imdb,imikolov,uci_housing,
movielens}.py.  The reference downloads from its mirror at construction;
this build has no network egress, so every dataset takes a local
`data_file` in the SAME archive format the reference downloads, and
parses it identically (tokenization, vocabulary building, rating tuples).
"""
from __future__ import annotations

import os
import re
import tarfile
import zipfile
from collections import Counter
from typing import Dict, List, Optional

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Movielens",
           "Conll05st", "WMT14", "WMT16"]


def _require(data_file: Optional[str], name: str) -> str:
    if data_file is None:
        raise ValueError(
            f"{name}: this build has no network egress; pass data_file= "
            f"pointing at the locally-downloaded archive")
    if not os.path.exists(data_file):
        raise FileNotFoundError(data_file)
    return data_file


class Imdb(Dataset):
    """IMDB sentiment (aclImdb tar.gz layout: aclImdb/<mode>/<pos|neg>/
    *.txt).  Builds the word vocab from the archive like imdb.py, yields
    (ids int64 array, label 0/1)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150):
        data_file = _require(data_file, "Imdb")
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be train|test, got {mode!r}")
        self.mode = mode
        # vocabulary spans BOTH splits (imdb.py build_dict scans
        # train|test) so train/test instances share word ids; `cutoff` is a
        # minimum-frequency threshold, not a vocab size
        vocab_pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        tokenize = re.compile(r"[A-Za-z0-9']+")
        texts: List[List[str]] = []
        labels: List[int] = []
        counter: Counter = Counter()
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                vm = vocab_pat.search(member.name)
                if not vm:
                    continue
                words = tokenize.findall(
                    tf.extractfile(member).read().decode(
                        "utf-8", "ignore").lower())
                counter.update(words)
                m = pat.search(member.name)
                if m:
                    texts.append(words)
                    labels.append(0 if m.group(1) == "neg" else 1)
        # frequency-sorted vocab above the cutoff (alphabetical on ties,
        # matching the reference's (-count, word) sort), <unk> = last id
        vocab_words = [w for w, c in sorted(counter.items(),
                                            key=lambda kv: (-kv[1], kv[0]))
                       if c > cutoff]
        self.word_idx: Dict[str, int] = {w: i for i, w in
                                         enumerate(vocab_words)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.array([self.word_idx.get(w, unk) for w in words],
                              dtype=np.int64) for words in texts]
        self.labels = np.array(labels, dtype=np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB language-model n-grams (imikolov.py): simple-examples tar.gz
    with ./data/ptb.{train,valid}.txt; data_type NGRAM -> sliding windows
    of `window_size`, SEQ -> whole <s> .. <e> sentences."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size: int = 5, mode: str = "train",
                 min_word_freq: int = 50):
        data_file = _require(data_file, "Imikolov")
        if data_type not in ("NGRAM", "SEQ"):
            raise ValueError("data_type must be NGRAM or SEQ")
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be train|test, got {mode!r}")
        split = {"train": "train", "test": "valid"}[mode]
        with tarfile.open(data_file, "r:*") as tf:
            train_lines = self._lines(tf, "ptb.train.txt")
            lines = train_lines if split == "train" else \
                self._lines(tf, "ptb.valid.txt")
        counter: Counter = Counter()
        for ln in train_lines:
            counter.update(["<s>"] + ln + ["<e>"])   # markers join the vocab
        counter.pop("<unk>", None)
        vocab = sorted((w for w, c in counter.items()
                        if c >= min_word_freq))
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data: List[np.ndarray] = []
        for ln in lines:
            ids = [self.word_idx.get(w, unk)
                   for w in (["<s>"] + ln + ["<e>"])]
            if data_type == "NGRAM":
                if len(ids) >= window_size:
                    for i in range(window_size, len(ids) + 1):
                        self.data.append(np.array(ids[i - window_size:i],
                                                  dtype=np.int64))
            else:
                self.data.append(np.array(ids, dtype=np.int64))

    @staticmethod
    def _lines(tf: tarfile.TarFile, name: str) -> List[List[str]]:
        member = next(m for m in tf.getmembers() if m.name.endswith(name))
        raw = tf.extractfile(member).read().decode("utf-8", "ignore")
        return [ln.strip().split() for ln in raw.splitlines() if ln.strip()]

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    """Boston housing regression (uci_housing.py): whitespace table of 14
    columns, feature-normalized, 80/20 train/test split."""

    FEATURE_DIM = 13

    def __init__(self, data_file: Optional[str] = None, mode: str = "train"):
        data_file = _require(data_file, "UCIHousing")
        raw = np.loadtxt(data_file).astype(np.float32)
        if raw.ndim != 2 or raw.shape[1] != self.FEATURE_DIM + 1:
            raise ValueError(
                f"UCIHousing expects {self.FEATURE_DIM + 1} columns, got "
                f"{raw.shape}")
        # normalize features by train-portion statistics (uci_housing.py
        # max/min/avg normalization)
        split = int(raw.shape[0] * 0.8)
        feats = raw[:, :-1]
        mx, mn, avg = (feats[:split].max(0), feats[:split].min(0),
                       feats[:split].mean(0))
        denom = np.where(mx - mn == 0, 1.0, mx - mn)
        feats = (feats - avg) / denom
        data = np.concatenate([feats, raw[:, -1:]], axis=1)
        self.data = data[:split] if mode == "train" else data[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """MovieLens-1M ratings (movielens.py): ml-1m.zip with users.dat /
    movies.dat / ratings.dat ('::' separated); yields (user_id, gender,
    age, job, movie_id, title_ids, category_ids, rating)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 test_ratio: float = 0.1, rand_seed: int = 0):
        data_file = _require(data_file, "Movielens")
        users: Dict[int, tuple] = {}
        movies: Dict[int, tuple] = {}
        with zipfile.ZipFile(data_file) as zf:
            def read(name):
                member = next(n for n in zf.namelist()
                              if n.endswith(name))
                return zf.read(member).decode("latin1").splitlines()

            categories: Dict[str, int] = {}
            title_words: Dict[str, int] = {}
            for ln in read("movies.dat"):
                mid, title, cats = ln.strip().split("::")
                cat_ids = [categories.setdefault(c, len(categories))
                           for c in cats.split("|")]
                tw = [title_words.setdefault(w, len(title_words))
                      for w in re.findall(r"[A-Za-z0-9']+", title.lower())]
                movies[int(mid)] = (np.array(tw, np.int64),
                                    np.array(cat_ids, np.int64))
            for ln in read("users.dat"):
                uid, gender, age, job, _zip = ln.strip().split("::")
                users[int(uid)] = (0 if gender == "M" else 1, int(age),
                                   int(job))
            rng = np.random.RandomState(rand_seed)
            self.samples = []
            for ln in read("ratings.dat"):
                uid, mid, rating, _ts = ln.strip().split("::")
                uid, mid = int(uid), int(mid)
                if uid not in users or mid not in movies:
                    continue
                is_test = rng.rand() < test_ratio
                if (mode == "test") != is_test:
                    continue
                g, a, j = users[uid]
                tw, cats = movies[mid]
                self.samples.append((np.int64(uid), np.int64(g),
                                     np.int64(a), np.int64(j),
                                     np.int64(mid), tw, cats,
                                     np.float32(rating)))
        self.categories_dict = categories
        self.movie_title_dict = title_words

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class Conll05st(Dataset):
    """CoNLL-2005 SRL test set (reference text/datasets/conll05.py:43).

    Parses the conll05st-release tar (words/props .gz members), builds the
    B-/I- label dict from the target dictionary file, and yields the
    9-tuple (word_idx, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_idx,
    mark, label_idx) with the reference's predicate-context windows.
    """

    UNK_IDX = 0

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 download=False):
        self.data_file = _require(data_file, "Conll05st")
        self.word_dict = self._load_dict(
            _require(word_dict_file, "Conll05st(word_dict_file)"))
        self.predicate_dict = self._load_dict(
            _require(verb_dict_file, "Conll05st(verb_dict_file)"))
        self.label_dict = self._load_label_dict(
            _require(target_dict_file, "Conll05st(target_dict_file)"))
        self.emb_file = emb_file
        self._load_anno()

    @staticmethod
    def _load_dict(filename):
        with open(filename) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    @staticmethod
    def _load_label_dict(filename):
        # the reference collects the B-/I- tag set then enumerates pairs,
        # closing with "O" (conll05.py _load_label_dict)
        tags = set()
        with open(filename) as f:
            for line in f:
                line = line.strip()
                if line.startswith(("B-", "I-")):
                    tags.add(line[2:])
        # sorted: set iteration order is hash-randomized per process, and
        # the label ids must be stable across save/load boundaries
        d, index = {}, 0
        for tag in sorted(tags):
            d["B-" + tag] = index
            d["I-" + tag] = index + 1
            index += 2
        d["O"] = index
        return d

    def _load_anno(self):
        import gzip

        self.sentences, self.predicates, self.labels = [], [], []
        with tarfile.open(self.data_file) as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words_file, \
                    gzip.GzipFile(fileobj=pf) as props_file:
                sentences, labels, one_seg = [], [], []
                for word, label in zip(words_file, props_file):
                    word = word.strip().decode()
                    label = label.strip().decode().split()
                    if label:
                        sentences.append(word)
                        one_seg.append(label)
                        continue
                    # end of sentence: transpose the per-token prop columns
                    for i in range(len(one_seg[0]) if one_seg else 0):
                        labels.append([x[i] for x in one_seg])
                    if labels:
                        verb_list = [x for x in labels[0] if x != "-"]
                        for i, lbl in enumerate(labels[1:]):
                            self.sentences.append(sentences)
                            self.predicates.append(verb_list[i])
                            self.labels.append(self._spans_to_bio(lbl))
                    sentences, labels, one_seg = [], [], []

    @staticmethod
    def _spans_to_bio(lbl):
        """Bracketed span column -> BIO sequence (conll05.py:200-225)."""
        cur_tag, in_bracket, seq = "O", False, []
        for tok in lbl:
            if tok == "*":
                seq.append("I-" + cur_tag if in_bracket else "O")
            elif tok == "*)":
                seq.append("I-" + cur_tag)
                in_bracket = False
            elif "(" in tok and ")" in tok:
                cur_tag = tok[1:tok.find("*")]
                seq.append("B-" + cur_tag)
                in_bracket = False
            elif "(" in tok:
                cur_tag = tok[1:tok.find("*")]
                seq.append("B-" + cur_tag)
                in_bracket = True
            else:
                raise RuntimeError(f"Unexpected label: {tok}")
        return seq

    def __getitem__(self, idx):
        sentence, labels = self.sentences[idx], self.labels[idx]
        predicate = self.predicates[idx]
        n = len(sentence)
        v = labels.index("B-V")
        mark = [0] * len(labels)
        ctx = {}
        for off, key, pad in ((-2, "n2", "bos"), (-1, "n1", "bos"),
                              (0, "0", None), (1, "p1", "eos"),
                              (2, "p2", "eos")):
            j = v + off
            if 0 <= j < len(labels):
                mark[j] = 1
                ctx[key] = sentence[j]
            else:
                ctx[key] = pad
        word_idx = [self.word_dict.get(w, self.UNK_IDX) for w in sentence]
        out = [np.array(word_idx)]
        for key in ("n2", "n1", "0", "p1", "p2"):
            out.append(np.array(
                [self.word_dict.get(ctx[key], self.UNK_IDX)] * n))
        # OOV predicates fall back to UNK like the word path; labels index
        # directly so a tag missing from the target dict fails loudly at
        # parse time instead of yielding object arrays of None
        out.append(np.array(
            [self.predicate_dict.get(predicate, self.UNK_IDX)] * n))
        out.append(np.array(mark))
        out.append(np.array([self.label_dict[w] for w in labels]))
        return tuple(out)

    def __len__(self):
        return len(self.sentences)

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict

    def get_embedding(self):
        return self.emb_file


class WMT14(Dataset):
    """WMT14 en-fr subset (reference text/datasets/wmt14.py): tar with
    {train,test,gen}/ members plus src.dict / trg.dict; yields
    (src_ids, trg_ids, trg_ids_next) with <s>/<e> wrapping and the
    reference's len>80 training filter."""

    START, END, UNK_IDX = "<s>", "<e>", 2

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=False):
        if mode.lower() not in ("train", "test", "gen"):
            raise ValueError(
                f"mode should be 'train', 'test' or 'gen', but got {mode}")
        self.mode = mode.lower()
        self.data_file = _require(data_file, "WMT14")
        assert dict_size > 0, "dict_size should be set as positive number"
        self.dict_size = dict_size
        self._load_data()

    def _load_data(self):
        def to_dict(fd, size):
            out = {}
            for i, line in enumerate(fd):
                if i >= size:
                    break
                out[line.strip().decode()] = i
            return out

        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as f:
            names = [m.name for m in f if m.name.endswith("src.dict")]
            assert len(names) == 1
            self.src_dict = to_dict(f.extractfile(names[0]), self.dict_size)
            names = [m.name for m in f if m.name.endswith("trg.dict")]
            assert len(names) == 1
            self.trg_dict = to_dict(f.extractfile(names[0]), self.dict_size)
            suffix = f"{self.mode}/{self.mode}"
            for name in [m.name for m in f if m.name.endswith(suffix)]:
                for line in f.extractfile(name):
                    parts = line.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_ids = [self.src_dict.get(w, self.UNK_IDX)
                               for w in [self.START] + parts[0].split()
                               + [self.END]]
                    trg_ids = [self.trg_dict.get(w, self.UNK_IDX)
                               for w in parts[1].split()]
                    if len(src_ids) > 80 or len(trg_ids) > 80:
                        continue
                    self.src_ids.append(src_ids)
                    self.trg_ids_next.append(trg_ids +
                                             [self.trg_dict[self.END]])
                    self.trg_ids.append([self.trg_dict[self.START]] + trg_ids)

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, reverse=False):
        if reverse:
            return ({v: k for k, v in self.src_dict.items()},
                    {v: k for k, v in self.trg_dict.items()})
        return self.src_dict, self.trg_dict


class WMT16(Dataset):
    """WMT16 Multi30K en-de (reference text/datasets/wmt16.py): tar with
    wmt16/{train,test,val}; builds frequency-ranked dicts headed by
    <s>/<e>/<unk> from the train split (cached beside the archive) and
    yields (src_ids, trg_ids, trg_ids_next)."""

    START_MARK, END_MARK, UNK_MARK = "<s>", "<e>", "<unk>"
    TOTAL_EN_WORDS, TOTAL_DE_WORDS = 11250, 19220

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=False):
        if mode.lower() not in ("train", "test", "val"):
            raise ValueError(
                f"mode should be 'train', 'test' or 'val', but got {mode}")
        self.mode = mode.lower()
        self.data_file = _require(data_file, "WMT16")
        self.lang = lang
        assert src_dict_size > 0 and trg_dict_size > 0, \
            "dict_size should be set as positive number"
        self.src_dict_size = min(src_dict_size, self.TOTAL_EN_WORDS
                                 if lang == "en" else self.TOTAL_DE_WORDS)
        self.trg_dict_size = min(trg_dict_size, self.TOTAL_DE_WORDS
                                 if lang == "en" else self.TOTAL_EN_WORDS)
        self.src_dict = self._load_dict(lang, self.src_dict_size)
        self.trg_dict = self._load_dict("de" if lang == "en" else "en",
                                        self.trg_dict_size)
        self._load_data()

    def _dict_path(self, lang, size):
        return os.path.join(os.path.dirname(os.path.abspath(self.data_file)),
                            f"wmt16_{lang}_{size}.dict")

    def _load_dict(self, lang, dict_size, reverse=False):
        path = self._dict_path(lang, dict_size)
        # the filename encodes dict_size, so any cache at this path was
        # built for this request; a corpus with fewer than dict_size
        # distinct words legitimately yields a shorter file (exact-length
        # checking would rebuild the dict on every construction)
        found = False
        if os.path.exists(path):
            with open(path, "rb") as d:
                n = len(d.readlines())
                found = 3 <= n <= dict_size
        if not found:
            self._build_dict(path, dict_size, lang)
        out = {}
        with open(path, "rb") as f:
            for idx, line in enumerate(f):
                word = line.strip().decode()
                if reverse:
                    out[idx] = word
                else:
                    out[word] = idx
        return out

    def _build_dict(self, path, dict_size, lang):
        counts = Counter()
        col = 0 if lang == "en" else 1
        with tarfile.open(self.data_file) as f:
            for line in f.extractfile("wmt16/train"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                counts.update(parts[col].split())
        with open(path, "w") as fout:
            fout.write(f"{self.START_MARK}\n{self.END_MARK}\n"
                       f"{self.UNK_MARK}\n")
            for idx, (word, _) in enumerate(counts.most_common()):
                if idx + 3 == dict_size:
                    break
                fout.write(word + "\n")

    def _load_data(self):
        start_id = self.src_dict[self.START_MARK]
        end_id = self.src_dict[self.END_MARK]
        unk_id = self.src_dict[self.UNK_MARK]
        src_col = 0 if self.lang == "en" else 1
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as f:
            for line in f.extractfile(f"wmt16/{self.mode}"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                src_ids = [start_id] + \
                    [self.src_dict.get(w, unk_id)
                     for w in parts[src_col].split()] + [end_id]
                trg_ids = [self.trg_dict.get(w, unk_id)
                           for w in parts[1 - src_col].split()]
                self.src_ids.append(src_ids)
                self.trg_ids_next.append(trg_ids + [end_id])
                self.trg_ids.append([start_id] + trg_ids)

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, lang, reverse=False):
        size = self.src_dict_size if lang == self.lang else self.trg_dict_size
        return self._load_dict(lang, size, reverse)
