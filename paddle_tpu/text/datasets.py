"""paddle.text.datasets parity — Imdb, Imikolov, UCIHousing, Movielens.

Reference: python/paddle/text/datasets/{imdb,imikolov,uci_housing,
movielens}.py.  The reference downloads from its mirror at construction;
this build has no network egress, so every dataset takes a local
`data_file` in the SAME archive format the reference downloads, and
parses it identically (tokenization, vocabulary building, rating tuples).
"""
from __future__ import annotations

import os
import re
import tarfile
import zipfile
from collections import Counter
from typing import Dict, List, Optional

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Movielens"]


def _require(data_file: Optional[str], name: str) -> str:
    if data_file is None:
        raise ValueError(
            f"{name}: this build has no network egress; pass data_file= "
            f"pointing at the locally-downloaded archive")
    if not os.path.exists(data_file):
        raise FileNotFoundError(data_file)
    return data_file


class Imdb(Dataset):
    """IMDB sentiment (aclImdb tar.gz layout: aclImdb/<mode>/<pos|neg>/
    *.txt).  Builds the word vocab from the archive like imdb.py, yields
    (ids int64 array, label 0/1)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150):
        data_file = _require(data_file, "Imdb")
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be train|test, got {mode!r}")
        self.mode = mode
        # vocabulary spans BOTH splits (imdb.py build_dict scans
        # train|test) so train/test instances share word ids; `cutoff` is a
        # minimum-frequency threshold, not a vocab size
        vocab_pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        tokenize = re.compile(r"[A-Za-z0-9']+")
        texts: List[List[str]] = []
        labels: List[int] = []
        counter: Counter = Counter()
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                vm = vocab_pat.search(member.name)
                if not vm:
                    continue
                words = tokenize.findall(
                    tf.extractfile(member).read().decode(
                        "utf-8", "ignore").lower())
                counter.update(words)
                m = pat.search(member.name)
                if m:
                    texts.append(words)
                    labels.append(0 if m.group(1) == "neg" else 1)
        # frequency-sorted vocab above the cutoff (alphabetical on ties,
        # matching the reference's (-count, word) sort), <unk> = last id
        vocab_words = [w for w, c in sorted(counter.items(),
                                            key=lambda kv: (-kv[1], kv[0]))
                       if c > cutoff]
        self.word_idx: Dict[str, int] = {w: i for i, w in
                                         enumerate(vocab_words)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.array([self.word_idx.get(w, unk) for w in words],
                              dtype=np.int64) for words in texts]
        self.labels = np.array(labels, dtype=np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB language-model n-grams (imikolov.py): simple-examples tar.gz
    with ./data/ptb.{train,valid}.txt; data_type NGRAM -> sliding windows
    of `window_size`, SEQ -> whole <s> .. <e> sentences."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size: int = 5, mode: str = "train",
                 min_word_freq: int = 50):
        data_file = _require(data_file, "Imikolov")
        if data_type not in ("NGRAM", "SEQ"):
            raise ValueError("data_type must be NGRAM or SEQ")
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be train|test, got {mode!r}")
        split = {"train": "train", "test": "valid"}[mode]
        with tarfile.open(data_file, "r:*") as tf:
            train_lines = self._lines(tf, "ptb.train.txt")
            lines = train_lines if split == "train" else \
                self._lines(tf, "ptb.valid.txt")
        counter: Counter = Counter()
        for ln in train_lines:
            counter.update(["<s>"] + ln + ["<e>"])   # markers join the vocab
        counter.pop("<unk>", None)
        vocab = sorted((w for w, c in counter.items()
                        if c >= min_word_freq))
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data: List[np.ndarray] = []
        for ln in lines:
            ids = [self.word_idx.get(w, unk)
                   for w in (["<s>"] + ln + ["<e>"])]
            if data_type == "NGRAM":
                if len(ids) >= window_size:
                    for i in range(window_size, len(ids) + 1):
                        self.data.append(np.array(ids[i - window_size:i],
                                                  dtype=np.int64))
            else:
                self.data.append(np.array(ids, dtype=np.int64))

    @staticmethod
    def _lines(tf: tarfile.TarFile, name: str) -> List[List[str]]:
        member = next(m for m in tf.getmembers() if m.name.endswith(name))
        raw = tf.extractfile(member).read().decode("utf-8", "ignore")
        return [ln.strip().split() for ln in raw.splitlines() if ln.strip()]

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    """Boston housing regression (uci_housing.py): whitespace table of 14
    columns, feature-normalized, 80/20 train/test split."""

    FEATURE_DIM = 13

    def __init__(self, data_file: Optional[str] = None, mode: str = "train"):
        data_file = _require(data_file, "UCIHousing")
        raw = np.loadtxt(data_file).astype(np.float32)
        if raw.ndim != 2 or raw.shape[1] != self.FEATURE_DIM + 1:
            raise ValueError(
                f"UCIHousing expects {self.FEATURE_DIM + 1} columns, got "
                f"{raw.shape}")
        # normalize features by train-portion statistics (uci_housing.py
        # max/min/avg normalization)
        split = int(raw.shape[0] * 0.8)
        feats = raw[:, :-1]
        mx, mn, avg = (feats[:split].max(0), feats[:split].min(0),
                       feats[:split].mean(0))
        denom = np.where(mx - mn == 0, 1.0, mx - mn)
        feats = (feats - avg) / denom
        data = np.concatenate([feats, raw[:, -1:]], axis=1)
        self.data = data[:split] if mode == "train" else data[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """MovieLens-1M ratings (movielens.py): ml-1m.zip with users.dat /
    movies.dat / ratings.dat ('::' separated); yields (user_id, gender,
    age, job, movie_id, title_ids, category_ids, rating)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 test_ratio: float = 0.1, rand_seed: int = 0):
        data_file = _require(data_file, "Movielens")
        users: Dict[int, tuple] = {}
        movies: Dict[int, tuple] = {}
        with zipfile.ZipFile(data_file) as zf:
            def read(name):
                member = next(n for n in zf.namelist()
                              if n.endswith(name))
                return zf.read(member).decode("latin1").splitlines()

            categories: Dict[str, int] = {}
            title_words: Dict[str, int] = {}
            for ln in read("movies.dat"):
                mid, title, cats = ln.strip().split("::")
                cat_ids = [categories.setdefault(c, len(categories))
                           for c in cats.split("|")]
                tw = [title_words.setdefault(w, len(title_words))
                      for w in re.findall(r"[A-Za-z0-9']+", title.lower())]
                movies[int(mid)] = (np.array(tw, np.int64),
                                    np.array(cat_ids, np.int64))
            for ln in read("users.dat"):
                uid, gender, age, job, _zip = ln.strip().split("::")
                users[int(uid)] = (0 if gender == "M" else 1, int(age),
                                   int(job))
            rng = np.random.RandomState(rand_seed)
            self.samples = []
            for ln in read("ratings.dat"):
                uid, mid, rating, _ts = ln.strip().split("::")
                uid, mid = int(uid), int(mid)
                if uid not in users or mid not in movies:
                    continue
                is_test = rng.rand() < test_ratio
                if (mode == "test") != is_test:
                    continue
                g, a, j = users[uid]
                tw, cats = movies[mid]
                self.samples.append((np.int64(uid), np.int64(g),
                                     np.int64(a), np.int64(j),
                                     np.int64(mid), tw, cats,
                                     np.float32(rating)))
        self.categories_dict = categories
        self.movie_title_dict = title_words

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)
