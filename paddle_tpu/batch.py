"""paddle.batch — reader batching (reference python/paddle/batch.py:18)."""
from __future__ import annotations

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    """Wrap a sample reader into a batched reader yielding lists of up to
    `batch_size` samples (drop_last drops the ragged tail)."""
    if batch_size <= 0 or batch_size != int(batch_size):
        raise ValueError(
            "batch_size should be a positive integer value, "
            f"but got batch_size={batch_size}")

    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
