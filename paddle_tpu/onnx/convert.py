"""jaxpr → ONNX GraphProto.

The reference exports models through the external paddle2onnx converter
(python/paddle/onnx/export.py → paddle2onnx.export over a translated
Program).  This build has no Program→ONNX translator to borrow, but it has
something better suited: the model's traced jaxpr.  The exporter walks the
jaxpr equations and emits one or more ONNX nodes per lax primitive,
recursing through call-like primitives (pjit / custom_vjp / remat), so any
model whose inference forward lowers to the supported primitive set exports
— the same coverage contract paddle2onnx has via its op mappers.

Opset 13 is targeted (ReduceSum takes axes as an input there; ReduceMax
still uses the attribute form).
"""
from __future__ import annotations

import itertools

import numpy as np

from . import proto


class UnsupportedPrimitive(NotImplementedError):
    pass


class _Builder:
    def __init__(self):
        self.nodes: list[bytes] = []
        self.initializers: list[bytes] = []
        self._init_names: set[str] = set()
        self._counter = itertools.count()

    def name(self, hint="t"):
        return f"{hint}_{next(self._counter)}"

    def add_node(self, op, inputs, outputs, attrs=b""):
        self.nodes.append(proto.node(op, inputs, outputs,
                                     name=self.name(op.lower()), attrs=attrs))

    def add_initializer(self, arr, hint="const"):
        nm = self.name(hint)
        self.initializers.append(proto.tensor_proto(nm, np.asarray(arr)))
        self._init_names.add(nm)
        return nm

    def emit(self, op, inputs, attrs=b"", n_out=1, hint=None):
        outs = [self.name(hint or op.lower()) for _ in range(n_out)]
        self.add_node(op, inputs, outs, attrs)
        return outs[0] if n_out == 1 else outs


def _ints_attr(name, vals):
    return proto.attribute(name, [int(v) for v in vals])


def _axes_attrs(axes, keepdims=0):
    return _ints_attr("axes", axes) + proto.attribute("keepdims", keepdims)


# -- primitive handlers -------------------------------------------------------
# each: handler(builder, eqn, in_names:list[str], avals_in) -> list[str]

_SIMPLE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow",
    "neg": "Neg", "exp": "Exp", "log": "Log", "tanh": "Tanh",
    "logistic": "Sigmoid", "sqrt": "Sqrt", "abs": "Abs", "sign": "Sign",
    "floor": "Floor", "ceil": "Ceil", "round": "Round", "erf": "Erf",
    "sin": "Sin", "cos": "Cos", "tan": "Tan", "asin": "Asin",
    "acos": "Acos", "atan": "Atan", "sinh": "Sinh", "cosh": "Cosh",
    "and": "And", "or": "Or", "not": "Not", "xor": "Xor",
    "stop_gradient": "Identity", "copy": "Identity",
    "device_put": "Identity",
}

_COMPARE = {"eq": ("Equal", False), "lt": ("Less", False),
            "le": ("LessOrEqual", False), "gt": ("Greater", False),
            "ge": ("GreaterOrEqual", False), "ne": ("Equal", True)}


def _dot_general(b, eqn, ins, avals):
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    lhs, rhs = avals
    lr, rr = len(lhs.shape), len(rhs.shape)
    # plain / batched matmul: contract lhs last dim with rhs dim b+0,
    # batch dims leading and aligned — ONNX MatMul's numpy semantics
    if (list(lb) == list(range(len(lb))) and list(rb) == list(range(len(rb)))
            and list(lc) == [lr - 1] and list(rc) == [len(rb)]
            and lr >= 2 and rr >= 2):
        return [b.emit("MatMul", ins)]
    # anything else: Einsum with an equation derived from the dim numbers
    letters = itertools.cycle("abcdefghijklmnopqrstuvwxyz")
    lhs_l = [next(letters) for _ in range(lr)]
    rhs_l = [None] * rr
    for i, j in zip(lb, rb):
        rhs_l[j] = lhs_l[i]
    for i, j in zip(lc, rc):
        rhs_l[j] = lhs_l[i]
    for j in range(rr):
        if rhs_l[j] is None:
            rhs_l[j] = next(letters)
    out_l = [lhs_l[i] for i in lb] + \
        [lhs_l[i] for i in range(lr) if i not in set(lb) | set(lc)] + \
        [rhs_l[j] for j in range(rr) if j not in set(rb) | set(rc)]
    eq = f"{''.join(lhs_l)},{''.join(rhs_l)}->{''.join(out_l)}"
    return [b.emit("Einsum", ins, proto.attribute("equation", eq))]


def _broadcast_in_dim(b, eqn, ins, avals):
    shape = [int(d) for d in eqn.params["shape"]]
    bcast = list(eqn.params["broadcast_dimensions"])
    interm = [1] * len(shape)
    for src, dst in enumerate(bcast):
        interm[dst] = int(avals[0].shape[src])
    cur = ins[0]
    if list(avals[0].shape) != interm:
        shp = b.add_initializer(np.asarray(interm, np.int64), "shape")
        cur = b.emit("Reshape", [cur, shp])
    if interm != shape:
        tgt = b.add_initializer(np.asarray(shape, np.int64), "shape")
        cur = b.emit("Expand", [cur, tgt])
    elif cur is ins[0] and list(avals[0].shape) == interm:
        cur = b.emit("Identity", [cur])
    return [cur]


def _conv(b, eqn, ins, avals):
    p = eqn.params
    dn = p["dimension_numbers"]
    nd = len(avals[0].shape) - 2
    if dn.lhs_spec != tuple(range(nd + 2)) or \
            dn.rhs_spec != tuple(range(nd + 2)) or \
            dn.out_spec != tuple(range(nd + 2)):
        raise UnsupportedPrimitive(
            "conv_general_dilated: only NCHW/OIHW layouts export to ONNX "
            f"(got {dn})")
    if any(d != 1 for d in p["lhs_dilation"]):
        raise UnsupportedPrimitive(
            "conv_general_dilated with lhs_dilation (transposed conv) is "
            "not exported; use a ConvTranspose-free forward")
    if p.get("batch_group_count", 1) != 1:
        raise UnsupportedPrimitive("conv batch_group_count != 1")
    pads = [lo for lo, _ in p["padding"]] + [hi for _, hi in p["padding"]]
    attrs = _ints_attr("strides", p["window_strides"])
    attrs += _ints_attr("pads", pads)
    attrs += _ints_attr("dilations", p["rhs_dilation"])
    attrs += proto.attribute("group", int(p.get("feature_group_count", 1)))
    return [b.emit("Conv", ins, attrs)]


def _reduce_window(b, eqn, ins, avals, kind):
    p = eqn.params
    wd = list(p["window_dimensions"])
    ws = list(p["window_strides"])
    pad = list(p["padding"])
    if len(wd) < 3 or any(d != 1 for d in wd[:2]) or \
            any(s != 1 for s in ws[:2]) or any(pad[i] != (0, 0)
                                               for i in range(2)):
        raise UnsupportedPrimitive(
            f"reduce_window over non-spatial dims ({wd}) has no ONNX pool")
    if any(d != 1 for d in p.get("window_dilation", [1] * len(wd))) or \
            any(d != 1 for d in p.get("base_dilation", [1] * len(wd))):
        raise UnsupportedPrimitive("dilated reduce_window")
    kshape = wd[2:]
    pads = [lo for lo, _ in pad[2:]] + [hi for _, hi in pad[2:]]
    attrs = _ints_attr("kernel_shape", kshape)
    attrs += _ints_attr("strides", ws[2:])
    attrs += _ints_attr("pads", pads)
    if kind == "max":
        return [b.emit("MaxPool", ins, attrs)]
    # reduce_window_sum == AveragePool(count_include_pad=1) * window_size
    attrs += proto.attribute("count_include_pad", 1)
    avg = b.emit("AveragePool", ins, attrs)
    scale = b.add_initializer(
        np.asarray(float(np.prod(kshape)),
                   np.dtype(str(avals[0].dtype))), "winsize")
    return [b.emit("Mul", [avg, scale])]


def _pad(b, eqn, ins, avals):
    cfg = eqn.params["padding_config"]
    if any(interior != 0 for _, _, interior in cfg):
        raise UnsupportedPrimitive("interior pad has no ONNX equivalent")
    if any(lo < 0 or hi < 0 for lo, hi, _ in cfg):
        raise UnsupportedPrimitive("negative pad (slice) not exported")
    pads = [lo for lo, _, _ in cfg] + [hi for _, hi, _ in cfg]
    pads_init = b.add_initializer(np.asarray(pads, np.int64), "pads")
    return [b.emit("Pad", [ins[0], pads_init, ins[1]])]


def _reduce(b, eqn, ins, avals, onnx_op, axes_as_input):
    axes = [int(a) for a in eqn.params["axes"]]
    if axes_as_input:                       # opset-13 ReduceSum form
        ax = b.add_initializer(np.asarray(axes, np.int64), "axes")
        return [b.emit(onnx_op, [ins[0], ax],
                       proto.attribute("keepdims", 0))]
    return [b.emit(onnx_op, ins, _axes_attrs(axes))]


def convert_jaxpr(closed_jaxpr, input_names, const_names=None,
                  graph_name="paddle_tpu_graph", output_names=None,
                  opset=13):
    """ClosedJaxpr → serialized ONNX ModelProto bytes.

    input_names name the jaxpr's invars (ONNX graph inputs); consts become
    initializers (const_names may give them stable names, e.g. parameter
    state-dict keys).  `opset` is declared in the emitted opset_import (the
    node forms written here are opset-13 ones, valid in every later opset).
    """
    from jax._src import core as jcore

    b = _Builder()
    jaxpr = closed_jaxpr.jaxpr
    env: dict = {}

    def read(atom, hint="lit", peer_dtype=None):
        if isinstance(atom, jcore.Literal):
            val = np.asarray(atom.val)
            if val.dtype == np.float64:
                val = val.astype(np.float32)
            if val.dtype == np.int64 and atom.aval.weak_type:
                # weak-typed python int literal: follow the peer operand's
                # integer dtype (strict ONNX runtimes reject mixed-dtype
                # binary nodes — an int64 peer must see an int64 literal);
                # int32 only when no integer peer pins it wider
                if peer_dtype is not None and \
                        np.issubdtype(peer_dtype, np.integer):
                    val = val.astype(peer_dtype)
                else:
                    val = val.astype(np.int32)
            return b.add_initializer(val, hint)
        return env[atom]

    def _peer_dtype(invars, i):
        """dtype of the first non-literal sibling operand (binary-op peer)."""
        for j, a in enumerate(invars):
            if j != i and not isinstance(a, jcore.Literal):
                return np.dtype(a.aval.dtype)
        return None

    for i, v in enumerate(jaxpr.invars):
        env[v] = input_names[i]
    for i, (cv, cval) in enumerate(zip(jaxpr.constvars, closed_jaxpr.consts)):
        nm = (const_names[i] if const_names and i < len(const_names)
              else None) or b.name("param")
        arr = np.asarray(cval)
        if arr.dtype not in proto.DTYPE_TO_ONNX:
            raise UnsupportedPrimitive(
                f"onnx export: parameter dtype {arr.dtype} (cast the model "
                f"to float32/float16 first)")
        b.initializers.append(proto.tensor_proto(nm, arr))
        b._init_names.add(nm)
        env[cv] = nm

    def walk(jaxpr_inner, consts_env):
        for eqn in jaxpr_inner.eqns:
            _emit_eqn(eqn)

    def _emit_eqn(eqn):
        prim = str(eqn.primitive)
        ins = [read(a, peer_dtype=_peer_dtype(eqn.invars, i))
               for i, a in enumerate(eqn.invars)]
        avals = [a.aval for a in eqn.invars]

        # call-like primitives: inline the inner jaxpr
        inner = None
        if prim in ("pjit", "closed_call", "core_call", "remat",
                    "checkpoint", "custom_jvp_call", "custom_vjp_call",
                    "custom_vjp_call_jaxpr", "jit"):
            inner = (eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
                     or eqn.params.get("fun_jaxpr"))
        if inner is not None:
            ij = inner.jaxpr if hasattr(inner, "jaxpr") else inner
            iconsts = getattr(inner, "consts", [])
            for cv, cval in zip(ij.constvars, iconsts):
                env[cv] = b.add_initializer(np.asarray(cval), "param")
            # custom_vjp/jvp pass extra non-array args first sometimes;
            # align by trailing invars
            use_ins = ins[len(ins) - len(ij.invars):]
            for v, nm in zip(ij.invars, use_ins):
                env[v] = nm
            walk(ij, None)
            for outer_v, inner_v in zip(eqn.outvars, ij.outvars):
                env[outer_v] = read(inner_v)
            return

        outs = None
        if prim in _SIMPLE:
            outs = [b.emit(_SIMPLE[prim], ins)]
        elif prim in _COMPARE:
            op, negate = _COMPARE[prim]
            o = b.emit(op, ins)
            outs = [b.emit("Not", [o])] if negate else [o]
        elif prim == "dot_general":
            outs = _dot_general(b, eqn, ins, avals)
        elif prim == "broadcast_in_dim":
            outs = _broadcast_in_dim(b, eqn, ins, avals)
        elif prim == "reshape":
            shp = b.add_initializer(
                np.asarray([int(d) for d in eqn.params["new_sizes"]],
                           np.int64), "shape")
            outs = [b.emit("Reshape", [ins[0], shp])]
        elif prim == "transpose":
            outs = [b.emit("Transpose", ins,
                           _ints_attr("perm", eqn.params["permutation"]))]
        elif prim == "convert_element_type":
            dt = np.dtype(eqn.params["new_dtype"])
            outs = [b.emit("Cast", ins,
                           proto.attribute("to",
                                           proto.DTYPE_TO_ONNX[dt]))]
        elif prim == "select_n":
            if len(ins) != 3:
                raise UnsupportedPrimitive(f"select_n with {len(ins)} cases")
            # select_n(pred, on_false, on_true) → Where(pred, on_true, on_false)
            outs = [b.emit("Where", [ins[0], ins[2], ins[1]])]
        elif prim == "reduce_sum":
            outs = _reduce(b, eqn, ins, avals, "ReduceSum", True)
        elif prim == "reduce_max":
            outs = _reduce(b, eqn, ins, avals, "ReduceMax", False)
        elif prim == "reduce_min":
            outs = _reduce(b, eqn, ins, avals, "ReduceMin", False)
        elif prim == "reduce_prod":
            outs = _reduce(b, eqn, ins, avals, "ReduceProd", False)
        elif prim == "argmax":
            axes = eqn.params["axes"]
            a = b.emit("ArgMax", [ins[0]],
                       proto.attribute("axis", int(axes[0])) +
                       proto.attribute("keepdims", 0))
            dt = np.dtype(eqn.params["index_dtype"])
            outs = [b.emit("Cast", [a],
                           proto.attribute("to", proto.DTYPE_TO_ONNX[dt]))]
        elif prim == "concatenate":
            outs = [b.emit("Concat", ins,
                           proto.attribute("axis",
                                           int(eqn.params["dimension"])))]
        elif prim == "slice":
            p = eqn.params
            starts = b.add_initializer(
                np.asarray(p["start_indices"], np.int64), "starts")
            ends = b.add_initializer(
                np.asarray(p["limit_indices"], np.int64), "ends")
            axes_i = b.add_initializer(
                np.asarray(range(len(p["start_indices"])), np.int64), "axes")
            steps = b.add_initializer(
                np.asarray(p["strides"] or [1] * len(p["start_indices"]),
                           np.int64), "steps")
            outs = [b.emit("Slice", [ins[0], starts, ends, axes_i, steps])]
        elif prim == "rev":
            # lax.rev == Slice with step -1 on the reversed dims
            dims = list(eqn.params["dimensions"])
            shape = avals[0].shape
            starts = b.add_initializer(
                np.asarray([int(shape[d]) - 1 for d in dims], np.int64),
                "starts")
            ends = b.add_initializer(
                np.asarray([-(int(shape[d]) + 1) for d in dims], np.int64),
                "ends")
            axes_i = b.add_initializer(np.asarray(dims, np.int64), "axes")
            steps = b.add_initializer(
                np.asarray([-1] * len(dims), np.int64), "steps")
            outs = [b.emit("Slice", [ins[0], starts, ends, axes_i, steps])]
        elif prim == "rem":
            # lax.rem is truncated remainder (sign of dividend) == fmod;
            # ONNX Mod defaults to Python-style modulo and requires fmod=1
            # for floats
            outs = [b.emit("Mod", ins, proto.attribute("fmod", 1))]
        elif prim == "rsqrt":
            s = b.emit("Sqrt", ins)
            outs = [b.emit("Reciprocal", [s])]
        elif prim == "square":
            outs = [b.emit("Mul", [ins[0], ins[0]])]
        elif prim == "erfc":
            e = b.emit("Erf", ins)
            one = b.add_initializer(
                np.asarray(1.0, np.dtype(str(avals[0].dtype))), "one")
            outs = [b.emit("Sub", [one, e])]
        elif prim == "log1p":
            one = b.add_initializer(
                np.asarray(1.0, np.dtype(str(avals[0].dtype))), "one")
            s = b.emit("Add", [ins[0], one])
            outs = [b.emit("Log", [s])]
        elif prim == "expm1":
            e = b.emit("Exp", ins)
            one = b.add_initializer(
                np.asarray(1.0, np.dtype(str(avals[0].dtype))), "one")
            outs = [b.emit("Sub", [e, one])]
        elif prim == "integer_pow":
            y = b.add_initializer(
                np.asarray(float(eqn.params["y"]),
                           np.dtype(str(avals[0].dtype))), "exponent")
            outs = [b.emit("Pow", [ins[0], y])]
        elif prim == "conv_general_dilated":
            outs = _conv(b, eqn, ins, avals)
        elif prim == "reduce_window_max":
            outs = _reduce_window(b, eqn, ins, avals, "max")
        elif prim == "reduce_window_sum":
            outs = _reduce_window(b, eqn, ins, avals, "sum")
        elif prim == "reduce_window":
            # generic form: (operand, init) + a reducer jaxpr; only a
            # single max/add reducer maps to an ONNX pool
            red = eqn.params["jaxpr"]
            red = red.jaxpr if hasattr(red, "jaxpr") else red
            kind = (str(red.eqns[0].primitive)
                    if len(red.eqns) == 1 else None)
            if kind not in ("max", "add"):
                raise UnsupportedPrimitive(
                    f"reduce_window with reducer {kind!r}")
            outs = _reduce_window(b, eqn, ins[:1], avals[:1],
                                  "max" if kind == "max" else "sum")
        elif prim == "pad":
            outs = _pad(b, eqn, ins, avals)
        elif prim == "iota":
            arr = np.reshape(
                np.arange(eqn.params["shape"][eqn.params["dimension"]],
                          dtype=np.dtype(eqn.params["dtype"])),
                [-1 if i == eqn.params["dimension"] else 1
                 for i in range(len(eqn.params["shape"]))])
            arr = np.broadcast_to(arr, eqn.params["shape"]).copy()
            outs = [b.emit("Identity",
                           [b.add_initializer(arr, "iota")])]
        else:
            raise UnsupportedPrimitive(
                f"onnx export: primitive {prim!r} has no ONNX mapping; "
                f"supported set: {sorted(_SIMPLE) + ['dot_general', 'conv', 'pool', 'reduce', 'reshape', 'transpose', 'select_n', '...']}")
        for v, nm in zip(eqn.outvars, outs):
            env[v] = nm

    walk(jaxpr, None)

    inputs_vi = [proto.value_info(input_names[i], np.dtype(v.aval.dtype),
                                  [int(d) for d in v.aval.shape])
                 for i, v in enumerate(jaxpr.invars)]
    out_names = []
    outputs_vi = []
    for i, v in enumerate(jaxpr.outvars):
        nm = read(v, "out")
        want = (output_names[i] if output_names and i < len(output_names)
                else f"output_{i}")
        # always re-alias through Identity so graph outputs have stable
        # names even when the outvar is an input/initializer/literal
        b.add_node("Identity", [nm], [want])
        out_names.append(want)
        outputs_vi.append(proto.value_info(
            want, np.dtype(v.aval.dtype), [int(d) for d in v.aval.shape]))

    g = proto.graph(b.nodes, graph_name, b.initializers, inputs_vi,
                    outputs_vi)
    return proto.model(g, opset=opset)
