"""Minimal ONNX protobuf wire-format writer/reader — no external deps.

The reference's paddle.onnx.export delegates to the external paddle2onnx
package (python/paddle/onnx/export.py); this build instead serializes the
ModelProto directly.  Only the message fields the exporter emits are
implemented, against the onnx.proto3 field numbers (ONNX IR v8 / opset 13).

Wire format recap (developers.google.com/protocol-buffers/docs/encoding):
tag = (field_number << 3) | wire_type; wire types used here are 0 (varint)
and 2 (length-delimited).  Floats/doubles ride in raw_data bytes, so wire
type 5/1 is never needed by the writer; the reader still decodes them for
round-trip completeness.
"""
from __future__ import annotations

import struct

import numpy as np

# -- onnx.TensorProto.DataType enum (onnx/onnx.proto3) ------------------------
DTYPE_TO_ONNX = {
    np.dtype(np.float32): 1, np.dtype(np.uint8): 2, np.dtype(np.int8): 3,
    np.dtype(np.uint16): 4, np.dtype(np.int16): 5, np.dtype(np.int32): 6,
    np.dtype(np.int64): 7, np.dtype(np.bool_): 9, np.dtype(np.float16): 10,
    np.dtype(np.float64): 11, np.dtype(np.uint32): 12,
    np.dtype(np.uint64): 13,
}
ONNX_TO_DTYPE = {v: k for k, v in DTYPE_TO_ONNX.items()}
BFLOAT16_ONNX = 16


# -- writer -------------------------------------------------------------------

def _varint(n: int) -> bytes:
    if n < 0:                      # proto3 int64: 10-byte two's complement
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def field_varint(num: int, value: int) -> bytes:
    return _varint(num << 3) + _varint(value)


def field_bytes(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def field_string(num: int, s: str) -> bytes:
    return field_bytes(num, s.encode("utf-8"))


def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in DTYPE_TO_ONNX:
        raise NotImplementedError(f"onnx export: dtype {arr.dtype}")
    out = b"".join(field_varint(1, int(d)) for d in arr.shape)
    out += field_varint(2, DTYPE_TO_ONNX[arr.dtype])
    out += field_string(8, name)
    out += field_bytes(9, arr.tobytes())
    return out


def _tensor_shape(shape) -> bytes:
    """TensorShapeProto: dim=1 (Dim: dim_value=1, dim_param=2)."""
    dims = b""
    for d in shape:
        if isinstance(d, int):
            dims += field_bytes(1, field_varint(1, d))
        else:                      # symbolic dim name
            dims += field_bytes(1, field_string(2, str(d)))
    return dims


def value_info(name: str, dtype: np.dtype, shape) -> bytes:
    """ValueInfoProto: name=1, type=2; TypeProto.tensor_type=1
    (elem_type=1, shape=2)."""
    tt = field_varint(1, DTYPE_TO_ONNX[np.dtype(dtype)])
    tt += field_bytes(2, _tensor_shape(shape))
    return field_string(1, name) + field_bytes(2, field_bytes(1, tt))


# AttributeProto.AttributeType enum values
_ATTR_FLOAT, _ATTR_INT, _ATTR_STRING, _ATTR_TENSOR = 1, 2, 3, 4
_ATTR_FLOATS, _ATTR_INTS, _ATTR_STRINGS = 6, 7, 8


def attribute(name: str, value) -> bytes:
    """One NodeProto attribute, returned already wrapped as NodeProto
    field 5 so handlers can concatenate attributes freely.
    AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8,
    strings=9, type=20."""
    out = field_string(1, name)
    if isinstance(value, bool):
        out += field_varint(3, int(value)) + field_varint(20, _ATTR_INT)
    elif isinstance(value, int):
        out += field_varint(3, value) + field_varint(20, _ATTR_INT)
    elif isinstance(value, float):
        out += _varint((2 << 3) | 5) + struct.pack("<f", value)
        out += field_varint(20, _ATTR_FLOAT)
    elif isinstance(value, str):
        out += field_bytes(4, value.encode()) + field_varint(20, _ATTR_STRING)
    elif isinstance(value, np.ndarray):
        out += field_bytes(5, tensor_proto("", value))
        out += field_varint(20, _ATTR_TENSOR)
    elif isinstance(value, (list, tuple)) and all(
            isinstance(v, int) for v in value):
        for v in value:
            out += field_varint(8, v)
        out += field_varint(20, _ATTR_INTS)
    else:
        raise NotImplementedError(f"onnx attribute {name}={value!r}")
    return field_bytes(5, out)


def node(op_type: str, inputs, outputs, name: str = "",
         attrs: bytes = b"") -> bytes:
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    out = b"".join(field_string(1, i) for i in inputs)
    out += b"".join(field_string(2, o) for o in outputs)
    if name:
        out += field_string(3, name)
    out += field_string(4, op_type)
    out += attrs
    return out


def graph(nodes, name, initializers, inputs, outputs) -> bytes:
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    out = b"".join(field_bytes(1, n) for n in nodes)
    out += field_string(2, name)
    out += b"".join(field_bytes(5, t) for t in initializers)
    out += b"".join(field_bytes(11, i) for i in inputs)
    out += b"".join(field_bytes(12, o) for o in outputs)
    return out


def model(graph_bytes: bytes, opset: int = 13,
          producer: str = "paddle_tpu") -> bytes:
    """ModelProto: ir_version=1, producer_name=2, graph=7, opset_import=8
    (OperatorSetIdProto: domain=1, version=2)."""
    out = field_varint(1, 8)                        # IR version 8
    out += field_string(2, producer)
    out += field_bytes(7, graph_bytes)
    out += field_bytes(8, field_string(1, "") + field_varint(2, opset))
    return out


# -- reader (round-trip validation; generic field walker) ---------------------

def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def parse(buf: bytes):
    """Decode one message into {field_number: [values]}; length-delimited
    payloads stay raw bytes (caller re-parses known submessages)."""
    out: dict[int, list] = {}
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        num, wt = tag >> 3, tag & 7
        if wt == 0:
            val, pos = _read_varint(buf, pos)
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            val = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        elif wt == 1:
            val = struct.unpack("<d", buf[pos:pos + 8])[0]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        out.setdefault(num, []).append(val)
    return out


def parse_tensor(buf: bytes):
    """TensorProto bytes → (name, ndarray)."""
    f = parse(buf)
    dims = [int(d) for d in f.get(1, [])]
    dt = ONNX_TO_DTYPE[f[2][0]]
    name = f.get(8, [b""])[0].decode()
    arr = np.frombuffer(f[9][0], dtype=dt).reshape(dims) if 9 in f else \
        np.zeros(dims, dt)
    return name, arr


def parse_attribute(buf: bytes):
    """AttributeProto bytes → (name, python value)."""
    f = parse(buf)
    name = f[1][0].decode()
    atype = f.get(20, [0])[0]
    if atype == _ATTR_INT:
        return name, int(f[3][0]) - ((1 << 64) if f[3][0] >> 63 else 0)
    if atype == _ATTR_FLOAT:
        return name, float(f[2][0])
    if atype == _ATTR_STRING:
        return name, f[4][0].decode()
    if atype == _ATTR_TENSOR:
        return name, parse_tensor(f[5][0])[1]
    if atype == _ATTR_INTS:
        return name, [int(v) - ((1 << 64) if v >> 63 else 0)
                      for v in f.get(8, [])]
    raise NotImplementedError(f"attribute type {atype}")


def parse_value_info(buf: bytes):
    """ValueInfoProto bytes → (name, dtype, shape list[int|str])."""
    f = parse(buf)
    name = f[1][0].decode()
    tt = parse(parse(f[2][0])[1][0])
    elem = ONNX_TO_DTYPE[tt[1][0]]
    shape = []
    if 2 in tt:
        for dim_buf in parse(tt[2][0]).get(1, []):
            d = parse(dim_buf)
            shape.append(int(d[1][0]) if 1 in d else d[2][0].decode())
    return name, elem, shape


def parse_node(buf: bytes):
    """NodeProto bytes → dict(op_type, inputs, outputs, name, attrs)."""
    f = parse(buf)
    return {
        "op_type": f[4][0].decode(),
        "inputs": [b.decode() for b in f.get(1, [])],
        "outputs": [b.decode() for b in f.get(2, [])],
        "name": f.get(3, [b""])[0].decode(),
        "attrs": dict(parse_attribute(a) for a in f.get(5, [])),
    }


def parse_model(buf: bytes):
    """ModelProto bytes → dict with ir_version, opset, graph dict."""
    f = parse(buf)
    g = parse(f[7][0])
    opsets = []
    for o in f.get(8, []):
        of = parse(o)
        opsets.append((of.get(1, [b""])[0].decode(), int(of[2][0])))
    return {
        "ir_version": int(f[1][0]),
        "producer": f.get(2, [b""])[0].decode(),
        "opsets": opsets,
        "graph": {
            "name": g.get(2, [b""])[0].decode(),
            "nodes": [parse_node(n) for n in g.get(1, [])],
            "initializers": dict(parse_tensor(t) for t in g.get(5, [])),
            "inputs": [parse_value_info(v) for v in g.get(11, [])],
            "outputs": [parse_value_info(v) for v in g.get(12, [])],
        },
    }
