"""Reference evaluator for exported ONNX graphs — numpy only.

No ONNX runtime ships in this build, so exported models are validated by
executing the parsed GraphProto with numpy and comparing against the source
model's own forward.  Covers exactly the op set convert.py emits; it is a
test/verification tool, not a serving engine (serve via the inference
Predictor over jit.save artifacts)."""
from __future__ import annotations

import math

import numpy as np

from . import proto

_erf = np.vectorize(math.erf, otypes=[np.float64])


def _pool_view(x, kshape, strides, pads, fill):
    """Sliding windows over the trailing spatial dims of NC(H)W input →
    array of shape (*x_nc, *out_spatial, *kshape)."""
    nd = len(kshape)
    pad_width = [(0, 0)] * (x.ndim - nd) + [(lo, hi) for lo, hi in pads]
    xp = np.pad(x, pad_width, constant_values=fill)
    from numpy.lib.stride_tricks import sliding_window_view
    win = sliding_window_view(xp, kshape, axis=tuple(range(x.ndim - nd,
                                                           x.ndim)))
    idx = (slice(None),) * (x.ndim - nd) + tuple(
        slice(None, None, s) for s in strides)
    return win[idx]


def _conv(x, w, strides, pads, dilations, group):
    n, cin, *spatial = x.shape
    cout, cin_g, *kshape = w.shape
    nd = len(kshape)
    x = np.pad(x, [(0, 0), (0, 0)] + [(lo, hi) for lo, hi in
                                      zip(pads[:nd], pads[nd:])])
    out_sp = [(x.shape[2 + i] - (kshape[i] - 1) * dilations[i] - 1)
              // strides[i] + 1 for i in range(nd)]
    out = np.zeros((n, cout) + tuple(out_sp), np.result_type(x, w))
    cpg_out = cout // group
    for g in range(group):
        xs = x[:, g * cin_g:(g + 1) * cin_g]
        wsl = w[g * cpg_out:(g + 1) * cpg_out]
        for kidx in np.ndindex(*kshape):
            sl = (slice(None), slice(None)) + tuple(
                slice(kidx[i] * dilations[i],
                      kidx[i] * dilations[i] + out_sp[i] * strides[i],
                      strides[i]) for i in range(nd))
            patch = xs[sl]                      # n, cin_g, *out_sp
            wk = wsl[(slice(None), slice(None)) + kidx]   # cpg_out, cin_g
            out[:, g * cpg_out:(g + 1) * cpg_out] += np.einsum(
                "nc...,oc->no...", patch, wk)
    return out


def run(model_bytes: bytes, feeds: dict[str, np.ndarray]):
    """Execute a serialized ModelProto on numpy inputs; returns the list of
    graph outputs in declaration order."""
    m = proto.parse_model(model_bytes)
    g = m["graph"]
    env: dict[str, np.ndarray] = dict(g["initializers"])
    for name, dtype, shape in g["inputs"]:
        if name not in feeds:
            raise KeyError(f"missing graph input {name!r}")
        env[name] = np.asarray(feeds[name], dtype)

    for nd in g["nodes"]:
        op = nd["op_type"]
        a = nd["attrs"]
        x = [env[i] for i in nd["inputs"] if i]
        out = None
        if op == "Identity":
            out = x[0]
        elif op in ("Add", "Sub", "Mul", "Div", "Pow", "Mod"):
            fn = {"Add": np.add, "Sub": np.subtract, "Mul": np.multiply,
                  "Div": np.divide, "Pow": np.power, "Mod": np.fmod}[op]
            if op == "Div" and np.issubdtype(x[0].dtype, np.integer):
                out = (x[0] // x[1]).astype(x[0].dtype)
            else:
                out = fn(x[0], x[1]).astype(
                    np.result_type(x[0], x[1]), copy=False)
        elif op in ("Max", "Min"):
            fn = np.maximum if op == "Max" else np.minimum
            out = x[0]
            for other in x[1:]:
                out = fn(out, other)
        elif op in ("Neg", "Exp", "Log", "Tanh", "Sqrt", "Abs", "Sign",
                    "Floor", "Ceil", "Round", "Sin", "Cos", "Tan", "Asin",
                    "Acos", "Atan", "Sinh", "Cosh", "Reciprocal"):
            fn = {"Neg": np.negative, "Exp": np.exp, "Log": np.log,
                  "Tanh": np.tanh, "Sqrt": np.sqrt, "Abs": np.abs,
                  "Sign": np.sign, "Floor": np.floor, "Ceil": np.ceil,
                  "Round": np.round, "Sin": np.sin, "Cos": np.cos,
                  "Tan": np.tan, "Asin": np.arcsin, "Acos": np.arccos,
                  "Atan": np.arctan, "Sinh": np.sinh, "Cosh": np.cosh,
                  "Reciprocal": np.reciprocal}[op]
            out = fn(x[0]).astype(x[0].dtype, copy=False)
        elif op == "Sigmoid":
            out = (1.0 / (1.0 + np.exp(-x[0].astype(np.float64)))).astype(
                x[0].dtype)
        elif op == "Erf":
            out = _erf(x[0].astype(np.float64)).astype(x[0].dtype)
        elif op in ("And", "Or", "Xor"):
            fn = {"And": np.logical_and, "Or": np.logical_or,
                  "Xor": np.logical_xor}[op]
            out = fn(x[0], x[1])
        elif op == "Not":
            out = np.logical_not(x[0])
        elif op in ("Equal", "Less", "LessOrEqual", "Greater",
                    "GreaterOrEqual"):
            fn = {"Equal": np.equal, "Less": np.less,
                  "LessOrEqual": np.less_equal, "Greater": np.greater,
                  "GreaterOrEqual": np.greater_equal}[op]
            out = fn(x[0], x[1])
        elif op == "Where":
            out = np.where(x[0], x[1], x[2])
        elif op == "MatMul":
            out = np.matmul(x[0], x[1])
        elif op == "Einsum":
            out = np.einsum(a["equation"], *x)
        elif op == "Reshape":
            out = x[0].reshape([int(d) for d in x[1]])
        elif op == "Expand":
            out = np.broadcast_to(x[0], [int(d) for d in x[1]]).copy()
        elif op == "Transpose":
            out = np.transpose(x[0], a.get("perm"))
        elif op == "Cast":
            out = x[0].astype(proto.ONNX_TO_DTYPE[a["to"]])
        elif op == "Concat":
            out = np.concatenate(x, axis=a["axis"])
        elif op == "Slice":
            starts, ends, axes, steps = (x[1], x[2],
                                         x[3] if len(x) > 3 else None,
                                         x[4] if len(x) > 4 else None)
            axes = axes if axes is not None else np.arange(len(starts))
            steps = steps if steps is not None else np.ones(len(starts),
                                                            np.int64)
            sl = [slice(None)] * x[0].ndim
            for s, e, ax, st in zip(starts, ends, axes, steps):
                s, e, st = int(s), int(e), int(st)
                dim = x[0].shape[int(ax)]
                if st > 0:
                    e = min(e, dim)
                else:
                    e = None if e < -dim else e
                sl[int(ax)] = slice(s, e, st)
            out = x[0][tuple(sl)]
        elif op == "ReduceSum":
            axes = tuple(int(v) for v in x[1]) if len(x) > 1 else None
            out = x[0].sum(axis=axes, keepdims=bool(a.get("keepdims", 1)),
                           dtype=x[0].dtype)
        elif op in ("ReduceMax", "ReduceMin", "ReduceProd", "ReduceMean"):
            fn = {"ReduceMax": np.max, "ReduceMin": np.min,
                  "ReduceProd": np.prod, "ReduceMean": np.mean}[op]
            axes = tuple(a["axes"]) if "axes" in a else None
            out = fn(x[0], axis=axes,
                     keepdims=bool(a.get("keepdims", 1))).astype(x[0].dtype)
        elif op == "ArgMax":
            out = np.argmax(x[0], axis=a.get("axis", 0))
            if a.get("keepdims", 1):
                out = np.expand_dims(out, a.get("axis", 0))
            out = out.astype(np.int64)
        elif op == "Conv":
            kshape = a["kernel_shape"] if "kernel_shape" in a else \
                list(x[1].shape[2:])
            nd2 = len(kshape)
            out = _conv(x[0], x[1],
                        a.get("strides", [1] * nd2),
                        a.get("pads", [0] * 2 * nd2),
                        a.get("dilations", [1] * nd2),
                        a.get("group", 1))
            if len(x) > 2:      # bias
                out = out + x[2].reshape((1, -1) + (1,) * nd2)
            out = out.astype(x[0].dtype, copy=False)
        elif op == "MaxPool":
            k = a["kernel_shape"]
            nd2 = len(k)
            pads = a.get("pads", [0] * 2 * nd2)
            win = _pool_view(x[0], k, a.get("strides", [1] * nd2),
                             list(zip(pads[:nd2], pads[nd2:])),
                             -np.inf if np.issubdtype(
                                 x[0].dtype, np.floating)
                             else np.iinfo(x[0].dtype).min)
            out = win.max(axis=tuple(range(-nd2, 0))).astype(x[0].dtype)
        elif op == "AveragePool":
            k = a["kernel_shape"]
            nd2 = len(k)
            pads = a.get("pads", [0] * 2 * nd2)
            if not a.get("count_include_pad", 0) and any(pads):
                raise NotImplementedError(
                    "AveragePool count_include_pad=0 with padding")
            win = _pool_view(x[0], k, a.get("strides", [1] * nd2),
                             list(zip(pads[:nd2], pads[nd2:])), 0)
            out = win.mean(axis=tuple(range(-nd2, 0))).astype(x[0].dtype)
        elif op == "Pad":
            pads = [int(v) for v in x[1]]
            nd2 = x[0].ndim
            cval = x[2] if len(x) > 2 else 0
            out = np.pad(x[0], list(zip(pads[:nd2], pads[nd2:])),
                         constant_values=cval)
        else:
            raise NotImplementedError(f"onnx runtime: op {op!r}")
        for o_name in nd["outputs"]:
            env[o_name] = out
    return [env[name] for name, _, _ in g["outputs"]]
