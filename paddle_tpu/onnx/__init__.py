"""paddle.onnx parity (reference: python/paddle/onnx/export.py).

The reference is a thin wrapper over the external paddle2onnx converter.
This build ships its own converter: the layer's inference forward is traced
to a jaxpr and translated op-by-op into a real ONNX ModelProto (opset 13)
with a hand-rolled protobuf writer — no external deps.  Models whose
forward stays inside the supported primitive set (matmul/conv/pool/
elementwise/normalization — see convert.py) produce a loadable `.onnx`
file; anything else raises UnsupportedPrimitive naming the offending op.

Validation story (no onnxruntime in the image): onnx/proto.py parses the
emitted bytes back (structural round-trip) and onnx/runtime.py executes the
parsed graph with numpy so tests compare ONNX semantics against the source
model's forward.  jit.save (StableHLO) remains the native serving format.
"""
from __future__ import annotations

import numpy as np

from .convert import UnsupportedPrimitive, convert_jaxpr  # noqa: F401
from . import proto, runtime  # noqa: F401


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Export `layer` for serving.

    ``path`` ending in ``.onnx`` writes a real ONNX protobuf (static shapes
    required — give concrete dims in input_spec).  Any other path keeps the
    native route: StableHLO via jit.save (`.pdmodel`, loadable by
    paddle.jit.load and the inference Predictor)."""
    from .. import jit

    if not str(path).endswith(".onnx"):
        jit.save(layer, str(path), input_spec=input_spec)
        return str(path) + ".pdmodel"

    if opset_version == 9:
        # the reference paddle2onnx default; its node forms are a strict
        # subset of what 13 accepts here, so upgrade instead of raising
        import warnings

        warnings.warn(
            "onnx.export: opset_version=9 (the reference default) is "
            "emitted as opset 13 (this exporter's ReduceSum axes-as-input "
            "node forms need >= 13)")
        opset_version = 13
    elif opset_version < 13:
        raise ValueError(
            f"this exporter emits opset >= 13 (ReduceSum axes-as-input "
            f"node forms); got opset_version={opset_version}")

    import jax

    from ..core.tensor import Tensor
    from ..jit import _resolve_specs, _strip
    from ..jit import StaticFunction
    from ..nn.functional_call import _swapped_state, state_values
    from ..nn.layer_base import Layer

    if not isinstance(layer, Layer):
        raise TypeError("onnx.export expects a Layer")
    input_spec = _resolve_specs(layer, input_spec)
    shapes = []
    for s in input_spec:
        shape = tuple(s.shape)
        if any(d is None or (isinstance(d, int) and d < 0) for d in shape):
            raise ValueError(
                f"onnx export needs concrete input shapes; got {shape} — "
                f"pass input_spec with all dims fixed (dynamic batch is a "
                f"jit.save/StableHLO feature)")
        shapes.append((shape, np.dtype(str(s.dtype))))

    values = state_values(layer)
    const_items = sorted(values.items())
    const_names = [k for k, _ in const_items]
    const_vals = [v for _, v in const_items]
    fwd = layer.forward
    if isinstance(fwd, StaticFunction):
        fwd = fwd._fn

    from ..core.autograd import no_grad

    def fn(*args):
        ts = tuple(Tensor(a, _internal=True) for a in args)
        # inference export: no tape — some primitives (reduce_window) fail
        # the eager-vjp linearization under abstract tracing
        with no_grad(), _swapped_state(layer,
                                       dict(zip(const_names, const_vals))):
            out = fwd(*ts)
        return _strip(out)

    was_training = layer.training
    if was_training:
        layer.eval()      # export inference behavior (dropout off, BN stats)
    try:
        closed = jax.make_jaxpr(fn)(
            *[jax.ShapeDtypeStruct(sh, dt) for sh, dt in shapes])
    finally:
        if was_training:
            layer.train()

    # consts the tracer actually captured are a subset of the state dict;
    # match them back to parameter names by identity where possible
    name_by_id = {id(v): k for k, v in zip(const_names, const_vals)}
    names = [name_by_id.get(id(c)) for c in closed.consts]

    model_bytes = convert_jaxpr(
        closed, input_names=[f"input_{i}" for i in range(len(shapes))],
        const_names=names,
        graph_name=type(layer).__name__, opset=opset_version)
    with open(path, "wb") as f:
        f.write(model_bytes)
    return str(path)
