"""Pallas TPU kernels — the analog of the reference's fused CUDA op family
(paddle/fluid/operators/fused/) and KPS primitives (phi/kernels/primitive/)."""
