"""Fused LayerNorm->matmul as one Pallas TPU kernel.

docs/PERF.md's round-3 conclusion after three standalone-LN attempts: any
opaque LN boundary loses because XLA's LN fusions are load-bearing hubs —
the LN math must live INSIDE the consuming custom call.  Every LN in the
GPT/BERT block feeds a projection (norm1 -> qkv_proj, norm2 -> fc0), so
the fusable form is y = LN(x; g, b) @ W + bias: the matmul has to read
the normalized rows anyway, and the row stats are VPU work that overlaps
the MXU.  Forward = this kernel; backward = plain jnp (XLA fuses the
grad reductions with its neighbors exactly as before, which the round-3
measurements showed it must).

Reference analog: fused_attention_op.cu's pre-LN + qkv fusion
(paddle/fluid/operators/fused/fused_attention_op.cu).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import flash_attention as _fa  # shared interpret toggle

_ENABLED = False


def enable_ln_matmul(flag: bool):
    """Opt in to the fused kernel.  Enabling PROBE-COMPILES a canonical
    shape first: inside a jitted train step the pallas_call only traces —
    a Mosaic failure would otherwise surface at the OUTER step compile,
    where no per-op fallback can catch it.  If the probe fails, the flag
    stays off and a warning names the error."""
    global _ENABLED
    if not flag:
        _ENABLED = False
        return
    try:
        import jax.extend.backend as jexb
        platform = jexb.get_backend().platform
    except Exception:
        platform = jax.default_backend()
    if platform in ("tpu", "axon") and not _fa._INTERPRET:
        try:
            x = jnp.zeros((256, 256), jnp.bfloat16)
            g = jnp.ones((256,), jnp.float32)
            w = jnp.zeros((256, 256), jnp.bfloat16)
            jax.block_until_ready(_ln_matmul_fwd_impl(x, g, g, w, 1e-5))
        except Exception as e:
            import warnings
            warnings.warn(
                f"ln_matmul kernel probe failed on this backend "
                f"({type(e).__name__}: {e}); keeping the fused path OFF")
            _ENABLED = False
            return
    _ENABLED = True


def ln_matmul_enabled() -> bool:
    return _ENABLED


def _kernel(x_ref, g_ref, b_ref, w_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=1, keepdims=True)
    d = x - mu
    var = jnp.mean(d * d, axis=1, keepdims=True)
    rs = jax.lax.rsqrt(var + eps)
    xln = (d * rs * g_ref[...].astype(jnp.float32) +
           b_ref[...].astype(jnp.float32)).astype(x_ref.dtype)
    o_ref[...] = jax.lax.dot_general(
        xln, w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


_BN = 256    # rows per block
_BM = 4096   # output columns per block (GPT projections fit whole in VMEM)


def _pad(x, mult, axis):
    pad = (-x.shape[axis]) % mult
    if pad:
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        x = jnp.pad(x, widths)
    return x


def _ln_matmul_fwd_impl(x2, g, b, w, eps):
    n, k = x2.shape
    m = w.shape[1]
    bn = min(_BN, max(8, n))
    bm = min(_BM, max(128, m))
    xp = _pad(x2, bn, 0)
    wp = _pad(w, bm, 1)
    ni = xp.shape[0] // bn
    nj = wp.shape[1] // bm
    out = pl.pallas_call(
        functools.partial(_kernel, eps=eps),
        grid=(ni, nj),
        in_specs=[
            pl.BlockSpec((bn, k), lambda i, j: (i, j * 0)),
            pl.BlockSpec((k,), lambda i, j: (i * 0,)),
            pl.BlockSpec((k,), lambda i, j: (i * 0,)),
            pl.BlockSpec((k, bm), lambda i, j: (i * 0, j)),
        ],
        out_specs=pl.BlockSpec((bn, bm), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((xp.shape[0], wp.shape[1]), x2.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_fa._INTERPRET,
    )(xp, g, b, wp)
    return out[:n, :m]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _ln_matmul(x2, g, b, w, eps):
    return _ln_matmul_fwd_impl(x2, g, b, w, eps)


def _fwd(x2, g, b, w, eps):
    return _ln_matmul_fwd_impl(x2, g, b, w, eps), (x2, g, b, w)


def _bwd(eps, res, dy):
    # plain jnp: XLA fuses these reductions with their graph neighbors —
    # measured faster than any pallas LN-backward boundary (docs/PERF.md)
    x2, g, b, w = res
    xf = x2.astype(jnp.float32)
    mu = jnp.mean(xf, axis=1, keepdims=True)
    d = xf - mu
    var = jnp.mean(d * d, axis=1, keepdims=True)
    rs = jax.lax.rsqrt(var + eps)
    xhat = d * rs
    gf = g.astype(jnp.float32)
    xln = (xhat * gf + b.astype(jnp.float32)).astype(x2.dtype)
    dyf = dy
    dw = jax.lax.dot_general(xln, dyf, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dxln = jax.lax.dot_general(dyf, w, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)
    dgamma = jnp.sum(dxln * xhat, axis=0)
    dbeta = jnp.sum(dxln, axis=0)
    gg = dxln * gf
    m1 = jnp.mean(gg, axis=1, keepdims=True)
    m2 = jnp.mean(gg * xhat, axis=1, keepdims=True)
    dx = (rs * (gg - m1 - xhat * m2)).astype(x2.dtype)
    return (dx, dgamma.astype(g.dtype), dbeta.astype(b.dtype),
            dw.astype(w.dtype))


_ln_matmul.defvjp(_fwd, _bwd)


def ln_matmul(x, ln_weight, ln_bias, w, bias=None, eps=1e-5):
    """y = LayerNorm(x over last axis; ln_weight, ln_bias) @ w (+ bias).

    x: [..., K]; w: [K, M].  The bias add stays OUTSIDE the kernel so XLA
    fuses it with whatever consumes y.
    """
    shape = x.shape
    k = shape[-1]
    y = _ln_matmul(x.reshape(-1, k), ln_weight, ln_bias, w, float(eps))
    y = y.reshape(shape[:-1] + (w.shape[1],))
    if bias is not None:
        y = y + bias
    return y


def ln_matmul_ok(x, w, mesh_free: bool) -> bool:
    """Routing predicate: opt-in, lane-aligned dims, real accelerator,
    single-device only for now (no GSPMD partitioning rule is registered
    for the custom call)."""
    if not _ENABLED or not mesh_free:
        return False
    if x.shape[-1] % 128 or w.shape[1] % 128:
        return False
    if _fa._INTERPRET:
        return True
    try:
        import jax.extend.backend as jexb
        platform = jexb.get_backend().platform
    except Exception:
        platform = jax.default_backend()
    # TPU-class backends only: the kernel is built on pltpu.CompilerParams;
    # any other accelerator would fail Mosaic lowering at call time
    return platform in ("tpu", "axon")
