"""Fused LayerNorm as Pallas TPU kernels — the analog of the reference's
layer_norm CUDA kernels (paddle/phi/kernels/gpu/layer_norm_kernel.cu,
layer_norm_grad_kernel.cu), which fuse the row statistics, the affine and
the three backward reductions.

Measured verdict (docs/PERF.md): on the GPT-2-small bench this kernel is a
net LOSS (0.479 -> 0.457 MFU) — XLA's LN fusions look slow in isolation
(~10x off roofline) but they carry neighboring elementwise work (residual
adds, casts) that the opaque custom call forces back into separate passes.
The kernel therefore ships OFF by default (`enable_fused_layernorm(True)`
to opt in, e.g. for layouts where LN dominates); the measurement is kept
so the next tuning round doesn't re-learn it.

Layout: x flattened to [N, C]; C must be lane-aligned (%128).  Forward
saves per-row (mean, rstd) in f32 — the standard fused-LN decomposition.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import flash_attention as _fa  # shared _INTERPRET toggle


def _interpret():
    return _fa._INTERPRET


#: "off" | "full" (pallas fwd+bwd) | "bwd" (XLA fwd, pallas bwd).
#: "bwd" is the hybrid: the forward stays jnp so XLA keeps fusing it into
#: its neighbors (the reason "full" measured as a net loss), while the
#: backward — whose XLA reduce fusions run ~60x off roofline on the GPT
#: shapes (docs/PERF.md round-3 profile) — runs as the pallas kernel.
_MODE = "off"


def enable_fused_layernorm(flag):
    """False/"off" disables; any other truthy non-string (incl. True) =
    "full" (pallas fwd+bwd, the pre-mode behavior); "bwd" = hybrid (XLA
    forward, pallas backward)."""
    global _MODE
    if not flag:
        _MODE = "off"
    elif not isinstance(flag, str):
        _MODE = "full"
    elif flag in ("off", "full", "bwd"):
        _MODE = flag
    else:
        raise ValueError(
            f"enable_fused_layernorm: unknown mode {flag!r} "
            f"(expected off|full|bwd)")


def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mu_ref, rs_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=1, keepdims=True)
    d = x - mu
    var = jnp.mean(d * d, axis=1, keepdims=True)
    rs = jax.lax.rsqrt(var + eps)
    y = d * rs * w_ref[...].astype(jnp.float32) + \
        b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mu_ref[...] = mu
    rs_ref[...] = rs


def _ln_bwd_kernel(x_ref, w_ref, mu_ref, rs_ref, dy_ref,
                   dx_ref, dw_ref, db_ref, dw_acc, db_acc, *, nb):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_acc[...] = jnp.zeros_like(dw_acc)
        db_acc[...] = jnp.zeros_like(db_acc)

    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    mu, rs = mu_ref[...], rs_ref[...]
    xhat = (x - mu) * rs
    dyw = dy * w_ref[...].astype(jnp.float32)
    m1 = jnp.mean(dyw, axis=1, keepdims=True)
    m2 = jnp.mean(dyw * xhat, axis=1, keepdims=True)
    dx_ref[...] = (rs * (dyw - m1 - xhat * m2)).astype(dx_ref.dtype)
    dw_acc[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_acc[...] += jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(i == nb - 1)
    def _finish():
        dw_ref[...] = dw_acc[...]
        db_ref[...] = db_acc[...]


_ROWS = 512  # rows per block: (512, C) f32 tiles + temporaries in VMEM


def _pad_rows(x, rb):
    n = x.shape[0]
    pad = (-n) % rb
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    return x


def _ln_fwd_impl(x2, w, b, eps):
    n, c = x2.shape
    rb = min(_ROWS, max(8, n))
    xp = _pad_rows(x2, rb)
    npad = xp.shape[0]
    nb = npad // rb
    wmap = lambda i: (i * 0,)                      # noqa: E731
    y, mu, rs = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((rb, c), lambda i: (i, i * 0)),
            pl.BlockSpec((c,), wmap),
            pl.BlockSpec((c,), wmap),
        ],
        out_specs=[
            pl.BlockSpec((rb, c), lambda i: (i, i * 0)),
            pl.BlockSpec((rb, 1), lambda i: (i, i * 0)),
            pl.BlockSpec((rb, 1), lambda i: (i, i * 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((npad, c), x2.dtype),
            jax.ShapeDtypeStruct((npad, 1), jnp.float32),
            jax.ShapeDtypeStruct((npad, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=_interpret(),
    )(xp, w, b)
    return y[:n], mu[:n], rs[:n]


def _ln_bwd_impl(x2, w, mu, rs, dy, eps):
    n, c = x2.shape
    rb = min(_ROWS, max(8, n))
    xp = _pad_rows(x2, rb)
    dyp = _pad_rows(dy, rb)
    mup = _pad_rows(mu, rb)
    rsp = _pad_rows(rs, rb)
    npad = xp.shape[0]
    nb = npad // rb
    wmap = lambda i: (i * 0,)                      # noqa: E731
    omap = lambda i: (i * 0, i * 0)                # noqa: E731
    dx, dw, db = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, nb=nb),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((rb, c), lambda i: (i, i * 0)),
            pl.BlockSpec((c,), wmap),
            pl.BlockSpec((rb, 1), lambda i: (i, i * 0)),
            pl.BlockSpec((rb, 1), lambda i: (i, i * 0)),
            pl.BlockSpec((rb, c), lambda i: (i, i * 0)),
        ],
        out_specs=[
            pl.BlockSpec((rb, c), lambda i: (i, i * 0)),
            pl.BlockSpec((1, c), omap),
            pl.BlockSpec((1, c), omap),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((npad, c), dy.dtype),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, c), jnp.float32),
            pltpu.VMEM((1, c), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=_interpret(),
    )(xp, w, mup, rsp, dyp)
    return dx[:n], dw[0], db[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_ln(x2, w, b, eps):
    y, _, _ = _ln_fwd_impl(x2, w, b, eps)
    return y


def _fused_ln_fwd(x2, w, b, eps):
    y, mu, rs = _ln_fwd_impl(x2, w, b, eps)
    return y, (x2, w, mu, rs)


def _fused_ln_bwd(eps, res, dy):
    x2, w, mu, rs = res
    dx, dw, db = _ln_bwd_impl(x2, w, mu, rs, dy, eps)
    return dx, dw.astype(w.dtype), db.astype(w.dtype)


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def _jnp_ln(x2, w, b, eps):
    xf = x2.astype(jnp.float32)
    mu = jnp.mean(xf, axis=1, keepdims=True)
    d = xf - mu
    var = jnp.mean(d * d, axis=1, keepdims=True)
    rs = jax.lax.rsqrt(var + eps)
    y = (d * rs * w.astype(jnp.float32) +
         b.astype(jnp.float32)).astype(x2.dtype)
    return y, mu, rs


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _hybrid_ln(x2, w, b, eps):
    return _jnp_ln(x2, w, b, eps)[0]


def _hybrid_ln_fwd(x2, w, b, eps):
    y, mu, rs = _jnp_ln(x2, w, b, eps)
    return y, (x2, w, mu, rs)


_hybrid_ln.defvjp(_hybrid_ln_fwd, _fused_ln_bwd)


def layer_norm_fused(x, weight, bias, eps):
    """Fused LN over the LAST axis; x any rank >= 2, weight/bias [C]."""
    shape = x.shape
    c = shape[-1]
    x2 = x.reshape(-1, c)
    fn = _hybrid_ln if _MODE == "bwd" else _fused_ln
    y = fn(x2, weight, bias, float(eps))
    return y.reshape(shape)


def layer_norm_fused_ok(x, axes, weight, bias) -> bool:
    """Routing predicate: opt-in (see module docstring), last-axis-only
    affine LN, lane-aligned C, on a real accelerator (or interpret mode
    for tests)."""
    if _MODE == "off":
        return False
    if weight is None or bias is None or len(axes) != 1:
        return False
    if axes[0] != x.ndim - 1 or x.ndim < 2 or x.shape[-1] % 128:
        return False
    if _interpret():
        return True
    try:
        import jax.extend.backend as jexb
        platform = jexb.get_backend().platform
    except Exception:
        platform = jax.default_backend()
    return platform not in ("cpu",)
