"""Flash attention as Pallas TPU kernels — the framework's analog of the
reference's fused CUDA attention family (paddle/fluid/operators/fused/
fused_attention_op.cu, fmha_ref.h), which materialises the S×S score matrix.
Here the online-softmax tiling keeps scores in VMEM tiles only:

* forward: grid (B*H/nb, Tq/bq, Tk/bk) with VMEM accumulators carried across
  the kv-block grid dimension (TPU grids execute sequentially, so scratch
  persists across the innermost dimension).  `nb` heads are processed per
  grid invocation as a batched MXU contraction — per-invocation launch
  overhead dominates wall time at GPT head sizes (d=64 means each single-head
  tile is only ~17M MACs), so amortizing it 8-way is worth ~5x end-to-end;
* backward: two kernels (dq; dk/dv) recomputing the tile probabilities from
  the saved logsumexp — the standard flash-attention-2 decomposition;
* `jax.custom_vjp` ties them together so `jax.grad` through the train step
  uses the fused backward.

Layout [B, T, H, D] at the API (the reference fused-op convention), internally
[(B*H), T, D].  MXU work is f32-accumulated (`preferred_element_type`).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# interpret mode runs the kernels on CPU (tests / debugging); set via
# use_interpret_mode() before first call
_INTERPRET = False


def use_interpret_mode(flag: bool):
    global _INTERPRET
    _INTERPRET = bool(flag)


def _block_sizes(tq, tk):
    # measured on v5e: attention at GPT head sizes is VPU-bound (softmax
    # ops on the score tile), so bigger tiles win — a full 1024-row kv tile
    # enables the one-pass (no online-softmax carry) kernel path below
    bq = min(1024, tq)
    bk = min(1024, tk)
    return bq, bk


def _head_block(bh: int, bq: int, bk: int) -> int:
    """Heads per grid invocation: the largest divisor of bh with the f32
    score tile (nb, bq, bk) comfortably inside VMEM.

    The 16 MB figure budgets the score tile only; the exp/p temporary,
    q/k/v/o tiles and double buffering ride in the remaining headroom of
    the 100 MB vmem_limit_bytes.  The resulting hot config — nb=4 at
    bq=bk=1024 one-pass forward, nbf=2 fused backward — is validated on
    real v5e hardware by every `python bench.py` run (docs/PERF.md);
    Mosaic rejects at compile time (scoped-vmem OOM), not silently, if a
    future shape breaks the envelope."""
    budget = 16 * 1024 * 1024   # bytes for the f32 score tile
    for nb in (8, 4, 2, 1):
        if bh % nb == 0 and nb * bq * bk * 4 <= budget:
            return nb
    return 1


def _pad_to(x, axis, mult):
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _qk(q, k):
    """(nb,bq,d) x (nb,bk,d) -> scores (nb,bq,bk), f32."""
    return jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)


def _pv(p, v):
    """(nb,bq,bk) x (nb,bk,d) -> (nb,bq,d), f32."""
    return jax.lax.dot_general(
        p, v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)


def _tq_contract(a, b):
    """(nb,bq,bk) x (nb,bq,d) contracted over bq -> (nb,bk,d), f32."""
    return jax.lax.dot_general(
        a, b, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)


def _tile_mask(i, j, bq, bk, causal, offset, t_real, pad_cols):
    """None when no masking is needed (interior tile, no kv padding)."""
    mask = None
    if pad_cols:                # kv padding exists: mask the dead columns
        col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = col < t_real
    if causal:
        col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cm = col <= row + offset
        mask = cm if mask is None else (mask & cm)
    return None if mask is None else mask[None]  # broadcast over head dim


# -- forward ------------------------------------------------------------------

def _rld(ref):
    """Load a q/k/v tile.  3D blocks load as-is; 4D (nb, 1, b*, d) blocks —
    the role-sliced views of a fused [BH, 3, T, D] qkv operand — squeeze
    the singleton role dim."""
    x = ref[...]
    return x[:, 0] if x.ndim == 4 else x


def _scaled_scores(q, k, i, j, *, scale, causal, offset, bq, bk,
                   pad_cols, t_real):
    """Masked scaled scores for one tile.  The scale folds into the small
    (nb,bq,d) q operand instead of the (nb,bq,bk) score tile — 16x fewer
    VPU multiplies at d=64."""
    q = (q.astype(jnp.float32) * jnp.float32(scale)).astype(q.dtype)
    s = _qk(q, k)
    mask = _tile_mask(i, j, bq, bk, causal, offset, t_real, pad_cols)
    if mask is not None:
        s = jnp.where(mask, s, jnp.float32(_NEG_INF))
    return s


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *scratch,
                scale, causal, offset, bq, bk, nk, t_real, pad_cols):
    i, j = pl.program_id(1), pl.program_id(2)
    qv, kv, vv = _rld(q_ref), _rld(k_ref), _rld(v_ref)

    if nk == 1:
        # no scratch is declared for the one-pass path (scratch == ())
        # one-pass softmax: the whole kv row is in this tile, so the online
        # rescaling carry (alpha, running m/l broadcasts) is dead weight
        s = _scaled_scores(qv, kv, i, j, scale=scale, causal=causal,
                           offset=offset, bq=bq, bk=bk, pad_cols=pad_cols,
                           t_real=t_real)
        m = jnp.max(s, axis=2, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.maximum(jnp.sum(p, axis=2, keepdims=True),
                        jnp.float32(1e-30))
        o_ref[...] = (_pv(p.astype(vv.dtype), vv) / l).astype(
            o_ref.dtype)
        lse_ref[...] = m + jnp.log(l)
        return

    acc, m_i, l_i = scratch

    @pl.when(j == 0)
    def _init():
        m_i[:] = jnp.full_like(m_i, _NEG_INF)
        l_i[:] = jnp.zeros_like(l_i)
        acc[:] = jnp.zeros_like(acc)

    live = True
    if causal:
        # kv block strictly above the diagonal band → nothing to do
        live = j * bk <= i * bq + (bq - 1) + offset

    @pl.when(live)
    def _compute():
        s = _scaled_scores(qv, kv, i, j, scale=scale, causal=causal,
                           offset=offset, bq=bq, bk=bk, pad_cols=pad_cols,
                           t_real=t_real)
        m_prev = m_i[:, :, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_i[:, :, :1] + jnp.sum(p, axis=2, keepdims=True)
        acc[:] = acc[:] * alpha + _pv(p.astype(vv.dtype), vv)
        m_i[:] = jnp.broadcast_to(m_new, m_i.shape)
        l_i[:] = jnp.broadcast_to(l_new, l_i.shape)

    @pl.when(j == nk - 1)
    def _finish():
        l = jnp.maximum(l_i[:, :, :1], jnp.float32(1e-30))
        o_ref[...] = (acc[:] / l).astype(o_ref.dtype)
        lse_ref[...] = m_i[:, :, :1] + jnp.log(l)


def _flash_fwd(q, k, v, scale, causal):
    """q,k,v: [BH, T, D] → (out [BH,Tq,D], lse [BH,Tq,1])."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    bq, bk = _block_sizes(tq, tk)
    nb = _head_block(bh, bq, bk)
    qp = _pad_to(q, 1, bq)
    kp = _pad_to(k, 1, bk)
    vp = _pad_to(v, 1, bk)
    tqp, tkp = qp.shape[1], kp.shape[1]
    nq, nk = tqp // bq, tkp // bk
    offset = tk - tq  # causal diagonal shift for cached decode

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, offset=offset,
        bq=bq, bk=bk, nk=nk, t_real=tk, pad_cols=(tkp != tk))
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh // nb, nq, nk),
        in_specs=[
            pl.BlockSpec((nb, bq, d), lambda b, i, j: (b, i, j * 0)),
            pl.BlockSpec((nb, bk, d), lambda b, i, j: (b, j, i * 0)),
            pl.BlockSpec((nb, bk, d), lambda b, i, j: (b, j, i * 0)),
        ],
        out_specs=[
            pl.BlockSpec((nb, bq, d), lambda b, i, j: (b, i, j * 0)),
            pl.BlockSpec((nb, bq, 1), lambda b, i, j: (b, i, j * 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tqp, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tqp, 1), jnp.float32),
        ],
        scratch_shapes=[] if nk == 1 else [
            pltpu.VMEM((nb, bq, d), jnp.float32),
            pltpu.VMEM((nb, bq, 128), jnp.float32),
            pltpu.VMEM((nb, bq, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_INTERPRET,
    )(qp, kp, vp)
    return out[:, :tq], lse[:, :tq]  # lse: [BH, Tq, 1]


# -- backward -----------------------------------------------------------------

def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      *out_refs, scale, causal, offset,
                      bq, bk, t_real, pad_cols, fused_out=False):
    """Single-tile backward (nq == nk == 1): dq, dk, dv in one pass sharing
    one recomputation of s/p — the two-kernel split exists only to give
    each output a sequential accumulation dimension, which a single tile
    does not need.  With ``fused_out`` the three grads go into role slices
    of ONE (nbf, 3, bq, d) output block, so XLA materializes a single
    layout copy for d_qkv instead of three."""
    q, k, v = _rld(q_ref), _rld(k_ref), _rld(v_ref)
    do = do_ref[...]
    qs = (q.astype(jnp.float32) * jnp.float32(scale)).astype(q.dtype)
    s = _qk(qs, k)
    mask = _tile_mask(0, 0, bq, bk, causal, offset, t_real, pad_cols)
    if mask is not None:
        s = jnp.where(mask, s, jnp.float32(_NEG_INF))
    p = jnp.exp(s - lse_ref[...])
    pt = p.astype(do.dtype)
    dv = _tq_contract(pt, do)
    dp = _qk(do, v)
    ds = (p * (dp - delta_ref[...])).astype(q.dtype)  # scale folded below
    ks = (k.astype(jnp.float32) * jnp.float32(scale)).astype(q.dtype)
    dq = _pv(ds, ks)
    dk = _tq_contract(ds, qs)
    if fused_out:
        (dqkv_ref,) = out_refs
        dqkv_ref[:, 0] = dq.astype(dqkv_ref.dtype)
        dqkv_ref[:, 1] = dk.astype(dqkv_ref.dtype)
        dqkv_ref[:, 2] = dv.astype(dqkv_ref.dtype)
    else:
        dq_ref, dk_ref, dv_ref = out_refs
        dq_ref[...] = dq.astype(dq_ref.dtype)
        dk_ref[...] = dk.astype(dk_ref.dtype)
        dv_ref[...] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, offset, bq, bk, nk, t_real,
                   pad_cols):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = True
    if causal:
        live = j * bk <= i * bq + (bq - 1) + offset

    @pl.when(live)
    def _compute():
        q, k, v = _rld(q_ref), _rld(k_ref), _rld(v_ref)
        do = do_ref[...]
        s = _scaled_scores(q, k, i, j, scale=scale, causal=causal,
                           offset=offset, bq=bq, bk=bk, pad_cols=pad_cols,
                           t_real=t_real)
        p = jnp.exp(s - lse_ref[...])
        dp = _qk(do, v)                    # (nb, bq, bk)
        ds = p * (dp - delta_ref[...])     # scale folds into k below
        ks = (k.astype(jnp.float32) * jnp.float32(scale)).astype(k.dtype)
        dq_acc[:] += _pv(ds.astype(k.dtype), ks)

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[...] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *,
                    scale, causal, offset, bq, bk, nq, t_real, pad_cols):
    j, i = pl.program_id(1), pl.program_id(2)  # j: kv block, i: q block

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = True
    if causal:
        live = j * bk <= i * bq + (bq - 1) + offset

    @pl.when(live)
    def _compute():
        q, k, v = _rld(q_ref), _rld(k_ref), _rld(v_ref)
        do = do_ref[...]
        qs = (q.astype(jnp.float32) * jnp.float32(scale)).astype(q.dtype)
        s = _qk(qs, k)
        mask = _tile_mask(i, j, bq, bk, causal, offset, t_real, pad_cols)
        if mask is not None:
            s = jnp.where(mask, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse_ref[...])
        dv_acc[:] += _tq_contract(p.astype(do.dtype), do)
        dp = _qk(do, v)
        ds = p * (dp - delta_ref[...])     # scale folds into qs below
        dk_acc[:] += _tq_contract(ds.astype(q.dtype), qs)

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[...] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, scale, causal):
    bh, tq, d = q.shape
    tk = k.shape[1]
    bq, bk = _block_sizes(tq, tk)
    nb = _head_block(bh, bq, bk)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)  # [BH, Tq, 1]
    qp, dop = _pad_to(q, 1, bq), _pad_to(do, 1, bq)
    kp, vp = _pad_to(k, 1, bk), _pad_to(v, 1, bk)
    # pad lse with a huge value (and delta with zeros): padded q rows then
    # produce p=exp(-1e30-big)=0 contributions in the dkv kernel
    lsep = _pad_to(lse, 1, bq)
    lsep = lsep.at[:, tq:].set(1e30) if lsep.shape[1] > tq else lsep
    deltap = _pad_to(delta, 1, bq)
    tqp, tkp = qp.shape[1], kp.shape[1]
    nq, nk = tqp // bq, tkp // bk
    offset = tk - tq

    if nq == 1 and nk == 1:
        fused = functools.partial(
            _bwd_fused_kernel, scale=scale, causal=causal, offset=offset,
            bq=bq, bk=bk, t_real=tk, pad_cols=(tkp != tk))
        # one score tile per invocation: halve the head block vs the
        # split kernels' budget since dq/dk/dv tiles coexist in VMEM
        nbf = max(1, _head_block(bh, bq, bk) // 2)
        assert bh % nbf == 0  # nbf divides _head_block's pick, which divides bh
        # NOTE: index maps must reference the grid vars (b, i, j*0) — this
        # backend's Mosaic fails to legalize constant-only maps
        qmap = lambda b, i, j: (b, i, j * 0)       # noqa: E731
        kmap = lambda b, i, j: (b, j, i * 0)       # noqa: E731
        dq, dk, dv = pl.pallas_call(
            fused,
            grid=(bh // nbf, 1, 1),
            in_specs=[
                pl.BlockSpec((nbf, bq, d), qmap),
                pl.BlockSpec((nbf, bk, d), kmap),
                pl.BlockSpec((nbf, bk, d), kmap),
                pl.BlockSpec((nbf, bq, d), qmap),
                pl.BlockSpec((nbf, bq, 1), qmap),
                pl.BlockSpec((nbf, bq, 1), qmap),
            ],
            out_specs=[
                pl.BlockSpec((nbf, bq, d), qmap),
                pl.BlockSpec((nbf, bk, d), kmap),
                pl.BlockSpec((nbf, bk, d), kmap),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, tqp, d), q.dtype),
                jax.ShapeDtypeStruct((bh, tkp, d), k.dtype),
                jax.ShapeDtypeStruct((bh, tkp, d), v.dtype),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary"),
                vmem_limit_bytes=100 * 1024 * 1024),
            interpret=_INTERPRET,
        )(qp, kp, vp, dop, lsep, deltap)
        return dq[:, :tq], dk[:, :tk], dv[:, :tk]

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, offset=offset,
        bq=bq, bk=bk, nk=nk, t_real=tk, pad_cols=(tkp != tk))
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh // nb, nq, nk),
        in_specs=[
            pl.BlockSpec((nb, bq, d), lambda b, i, j: (b, i, j * 0)),
            pl.BlockSpec((nb, bk, d), lambda b, i, j: (b, j, i * 0)),
            pl.BlockSpec((nb, bk, d), lambda b, i, j: (b, j, i * 0)),
            pl.BlockSpec((nb, bq, d), lambda b, i, j: (b, i, j * 0)),
            pl.BlockSpec((nb, bq, 1), lambda b, i, j: (b, i, j * 0)),
            pl.BlockSpec((nb, bq, 1), lambda b, i, j: (b, i, j * 0)),
        ],
        out_specs=pl.BlockSpec((nb, bq, d), lambda b, i, j: (b, i, j * 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tqp, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((nb, bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_INTERPRET,
    )(qp, kp, vp, dop, lsep, deltap)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, offset=offset,
        bq=bq, bk=bk, nq=nq, t_real=tk, pad_cols=(tkp != tk))
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh // nb, nk, nq),
        in_specs=[
            pl.BlockSpec((nb, bq, d), lambda b, j, i: (b, i, j * 0)),
            pl.BlockSpec((nb, bk, d), lambda b, j, i: (b, j, i * 0)),
            pl.BlockSpec((nb, bk, d), lambda b, j, i: (b, j, i * 0)),
            pl.BlockSpec((nb, bq, d), lambda b, j, i: (b, i, j * 0)),
            pl.BlockSpec((nb, bq, 1), lambda b, j, i: (b, i, j * 0)),
            pl.BlockSpec((nb, bq, 1), lambda b, j, i: (b, i, j * 0)),
        ],
        out_specs=[
            pl.BlockSpec((nb, bk, d), lambda b, j, i: (b, j, i * 0)),
            pl.BlockSpec((nb, bk, d), lambda b, j, i: (b, j, i * 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tkp, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tkp, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((nb, bk, d), jnp.float32),
            pltpu.VMEM((nb, bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_INTERPRET,
    )(qp, kp, vp, dop, lsep, deltap)
    return dq[:, :tq], dk[:, :tk], dv[:, :tk]


# -- fused-qkv drivers --------------------------------------------------------
#
# Layout [BH, 3, T, D]: ONE custom-call operand carries q, k and v.  The
# same array is passed three times with role-selecting index maps, so XLA
# materializes a single layout copy at the call boundary instead of three
# (docs/PERF.md layout-copy tax); the single-tile backward writes the three
# grads into role slices of one output for the same reason.

def _role_specs(nb, bq, bk, d):
    # NOTE: every index-map coordinate must involve a grid variable — this
    # backend's Mosaic fails to legalize constant-only coordinates
    # ("failed to legalize func.return", docs/PERF.md), so the role constants
    # are written j*0 + r
    qmap = lambda b, i, j: (b, j * 0, i, j * 0)            # noqa: E731
    kmap = lambda b, i, j: (b, i * 0 + 1, j, i * 0)        # noqa: E731
    vmap = lambda b, i, j: (b, i * 0 + 2, j, i * 0)        # noqa: E731
    return [pl.BlockSpec((nb, 1, bq, d), qmap),
            pl.BlockSpec((nb, 1, bk, d), kmap),
            pl.BlockSpec((nb, 1, bk, d), vmap)]


def _flash_fused_fwd_impl(qkv, scale, causal):
    """qkv: [BH, 3, T, D] → (out [BH, T, D], lse [BH, T, 1])."""
    bh, three, t, d = qkv.shape
    assert three == 3
    bq, bk = _block_sizes(t, t)
    nb = _head_block(bh, bq, bk)
    qkvp = _pad_to(qkv, 2, max(bq, bk))
    tp = qkvp.shape[2]
    nq, nk = tp // bq, tp // bk

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, offset=0,
        bq=bq, bk=bk, nk=nk, t_real=t, pad_cols=(tp != t))
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh // nb, nq, nk),
        in_specs=_role_specs(nb, bq, bk, d),
        out_specs=[
            pl.BlockSpec((nb, bq, d), lambda b, i, j: (b, i, j * 0)),
            pl.BlockSpec((nb, bq, 1), lambda b, i, j: (b, i, j * 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tp, d), qkv.dtype),
            jax.ShapeDtypeStruct((bh, tp, 1), jnp.float32),
        ],
        scratch_shapes=[] if nk == 1 else [
            pltpu.VMEM((nb, bq, d), jnp.float32),
            pltpu.VMEM((nb, bq, 128), jnp.float32),
            pltpu.VMEM((nb, bq, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_INTERPRET,
    )(qkvp, qkvp, qkvp)
    return out[:, :t], lse[:, :t]


def _flash_fused_bwd_impl(qkv, o, lse, do, scale, causal):
    bh, _, t, d = qkv.shape
    bq, bk = _block_sizes(t, t)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)
    qkvp = _pad_to(qkv, 2, max(bq, bk))
    dop = _pad_to(do, 1, bq)
    lsep = _pad_to(lse, 1, bq)
    lsep = lsep.at[:, t:].set(1e30) if lsep.shape[1] > t else lsep
    deltap = _pad_to(delta, 1, bq)
    tp = qkvp.shape[2]
    nq, nk = tp // bq, tp // bk

    if nq == 1 and nk == 1:
        fused = functools.partial(
            _bwd_fused_kernel, scale=scale, causal=causal, offset=0,
            bq=bq, bk=bk, t_real=t, pad_cols=(tp != t), fused_out=True)
        nbf = max(1, _head_block(bh, bq, bk) // 2)
        qmap3 = lambda b, i, j: (b, i, j * 0)      # noqa: E731
        dqkv = pl.pallas_call(
            fused,
            grid=(bh // nbf, 1, 1),
            in_specs=_role_specs(nbf, bq, bk, d) + [
                pl.BlockSpec((nbf, bq, d), qmap3),
                pl.BlockSpec((nbf, bq, 1), qmap3),
                pl.BlockSpec((nbf, bq, 1), qmap3),
            ],
            out_specs=pl.BlockSpec((nbf, 3, bq, d),
                                   lambda b, i, j: (b, j * 0, i, j * 0)),
            out_shape=jax.ShapeDtypeStruct((bh, 3, tp, d), qkv.dtype),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary"),
                vmem_limit_bytes=100 * 1024 * 1024),
            interpret=_INTERPRET,
        )(qkvp, qkvp, qkvp, dop, lsep, deltap)
        return dqkv[:, :, :t]

    # multi-tile fallback: role views through the split kernels, stacked at
    # the end (one extra copy — the single-tile path is the hot one)
    q3 = qkv[:, 0]
    k3 = qkv[:, 1]
    v3 = qkv[:, 2]
    dq, dk, dv = _flash_bwd(q3, k3, v3, o, lse, do, scale, causal)
    return jnp.stack([dq, dk, dv], axis=1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _flash_fused(qkv, scale, causal):
    out, _ = _flash_fused_fwd_impl(qkv, scale, causal)
    return out


def _flash_fused_fwd_rule(qkv, scale, causal):
    out, lse = _flash_fused_fwd_impl(qkv, scale, causal)
    return out, (qkv, out, lse)


def _flash_fused_bwd_rule(scale, causal, res, do):
    qkv, out, lse = res
    return (_flash_fused_bwd_impl(qkv, out, lse, do, scale, causal),)


_flash_fused.defvjp(_flash_fused_fwd_rule, _flash_fused_bwd_rule)


def flash_attention_qkv_fused(qkv, causal=True, scale=None):
    """Self-attention on the fused [BH, 3, T, D] qkv tensor (jax arrays)."""
    if scale is None:
        scale = 1.0 / math.sqrt(qkv.shape[-1])
    return _flash_fused(qkv, float(scale), bool(causal))


# -- custom_vjp glue ----------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, scale, causal):
    out, _ = _flash_fwd(q, k, v, scale, causal)
    return out


def _flash_fwd_rule(q, k, v, scale, causal):
    out, lse = _flash_fwd(q, k, v, scale, causal)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(scale, causal, res, do):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, do, scale, causal)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# -- public API ---------------------------------------------------------------

def flash_attention_bhtd(q, k, v, causal=True, scale=None):
    """q,k,v: [BH or (B,H), T, D] jax arrays, 3D."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, float(scale), bool(causal))


def flash_attention_bthd(q, k, v, causal=True, scale=None):
    """Paddle fused-op layout [B, T, H, D] (Tensor or jax.Array in/out)."""
    from ..core.op import apply_op
    from ..core.tensor import Tensor

    def raw(qv, kv, vv):
        b, tq, h, d = qv.shape
        tk = kv.shape[1]
        q3 = jnp.transpose(qv, (0, 2, 1, 3)).reshape(b * h, tq, d)
        k3 = jnp.transpose(kv, (0, 2, 1, 3)).reshape(b * h, tk, d)
        v3 = jnp.transpose(vv, (0, 2, 1, 3)).reshape(b * h, tk, d)
        o3 = flash_attention_bhtd(q3, k3, v3, causal=causal, scale=scale)
        return jnp.transpose(o3.reshape(b, h, tq, d), (0, 2, 1, 3))

    if isinstance(q, Tensor):
        return apply_op(raw, "flash_attention", (q, k, v), {})
    return raw(q, k, v)
