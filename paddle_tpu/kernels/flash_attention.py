"""Flash attention as Pallas TPU kernels — the framework's analog of the
reference's fused CUDA attention family (paddle/fluid/operators/fused/
fused_attention_op.cu, fmha_ref.h), which materialises the S×S score matrix.
Here the online-softmax tiling keeps scores in VMEM tiles only:

* forward: grid (B*H, Tq/bq, Tk/bk) with VMEM accumulators carried across the
  kv-block grid dimension (TPU grids execute sequentially, so scratch persists
  across the innermost dimension);
* backward: two kernels (dq; dk/dv) recomputing the tile probabilities from
  the saved logsumexp — the standard flash-attention-2 decomposition;
* `jax.custom_vjp` ties them together so `jax.grad` through the train step
  uses the fused backward.

Layout [B, T, H, D] at the API (the reference fused-op convention), internally
[(B*H), T, D].  MXU work is f32-accumulated (`preferred_element_type`).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# interpret mode runs the kernels on CPU (tests / debugging); set via
# use_interpret_mode() before first call
_INTERPRET = False


def use_interpret_mode(flag: bool):
    global _INTERPRET
    _INTERPRET = bool(flag)


def _block_sizes(tq, tk):
    bq = min(512, tq)
    bk = min(512, tk)
    return bq, bk


def _pad_to(x, axis, mult):
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


# -- forward ------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_i, l_i, *,
                scale, causal, offset, bq, bk, nk, t_real):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_i[:] = jnp.full_like(m_i, _NEG_INF)
        l_i[:] = jnp.zeros_like(l_i)
        acc[:] = jnp.zeros_like(acc)

    live = True
    if causal:
        # kv block strictly above the diagonal band → nothing to do
        live = j * bk <= i * bq + (bq - 1) + offset

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * jnp.float32(scale)
        col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = col < t_real
        if causal:
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            mask = mask & (col <= row + offset)
        s = jnp.where(mask, s, jnp.float32(_NEG_INF))

        m_prev = m_i[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_i[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_i[:] = jnp.broadcast_to(m_new, m_i.shape)
        l_i[:] = jnp.broadcast_to(l_new, l_i.shape)

    @pl.when(j == nk - 1)
    def _finish():
        l = jnp.maximum(l_i[:, :1], jnp.float32(1e-30))
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_i[:, :1] + jnp.log(l)


def _flash_fwd(q, k, v, scale, causal):
    """q,k,v: [BH, T, D] → (out [BH,Tq,D], lse [BH,Tq])."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    bq, bk = _block_sizes(tq, tk)
    qp = _pad_to(q, 1, bq)
    kp = _pad_to(k, 1, bk)
    vp = _pad_to(v, 1, bk)
    tqp, tkp = qp.shape[1], kp.shape[1]
    nq, nk = tqp // bq, tkp // bk
    offset = tk - tq  # causal diagonal shift for cached decode

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, offset=offset,
        bq=bq, bk=bk, nk=nk, t_real=tk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, j * 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, i * 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, i * 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, j * 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, j * 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tqp, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tqp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_INTERPRET,
    )(qp, kp, vp)
    return out[:, :tq], lse[:, :tq]  # lse: [BH, Tq, 1]


# -- backward -----------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, offset, bq, bk, nk, t_real):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = True
    if causal:
        live = j * bk <= i * bq + (bq - 1) + offset

    @pl.when(live)
    def _compute():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * jnp.float32(scale)
        col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = col < t_real
        if causal:
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            mask = mask & (col <= row + offset)
        s = jnp.where(mask, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse_ref[0])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * jnp.float32(scale)
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *,
                    scale, causal, offset, bq, bk, nq, t_real):
    j, i = pl.program_id(1), pl.program_id(2)  # j: kv block, i: q block

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = True
    if causal:
        live = j * bk <= i * bq + (bq - 1) + offset

    @pl.when(live)
    def _compute():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * jnp.float32(scale)
        col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = col < t_real
        if causal:
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            mask = mask & (col <= row + offset)
        s = jnp.where(mask, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse_ref[0])
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * jnp.float32(scale)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, scale, causal):
    bh, tq, d = q.shape
    tk = k.shape[1]
    bq, bk = _block_sizes(tq, tk)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)  # [BH, Tq, 1]
    qp, dop = _pad_to(q, 1, bq), _pad_to(do, 1, bq)
    kp, vp = _pad_to(k, 1, bk), _pad_to(v, 1, bk)
    # pad lse with a huge value (and delta with zeros): padded q rows then
    # produce p=exp(-1e30-big)=0 contributions in the dkv kernel
    lsep = _pad_to(lse, 1, bq)
    lsep = lsep.at[:, tq:].set(1e30) if lsep.shape[1] > tq else lsep
    deltap = _pad_to(delta, 1, bq)
    tqp, tkp = qp.shape[1], kp.shape[1]
    nq, nk = tqp // bq, tkp // bk
    offset = tk - tq

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, offset=offset,
        bq=bq, bk=bk, nk=nk, t_real=tk)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, j * 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, i * 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, i * 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, j * 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, j * 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, j * 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, j * 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tqp, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_INTERPRET,
    )(qp, kp, vp, dop, lsep, deltap)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, offset=offset,
        bq=bq, bk=bk, nq=nq, t_real=tk)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, j * 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, i * 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, i * 0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, j * 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, j * 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, j * 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, i * 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, i * 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tkp, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tkp, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_INTERPRET,
    )(qp, kp, vp, dop, lsep, deltap)
    return dq[:, :tq], dk[:, :tk], dv[:, :tk]


# -- custom_vjp glue ----------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, scale, causal):
    out, _ = _flash_fwd(q, k, v, scale, causal)
    return out


def _flash_fwd_rule(q, k, v, scale, causal):
    out, lse = _flash_fwd(q, k, v, scale, causal)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(scale, causal, res, do):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, do, scale, causal)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# -- public API ---------------------------------------------------------------

def flash_attention_bhtd(q, k, v, causal=True, scale=None):
    """q,k,v: [BH or (B,H), T, D] jax arrays, 3D."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, float(scale), bool(causal))


def flash_attention_bthd(q, k, v, causal=True, scale=None):
    """Paddle fused-op layout [B, T, H, D] (Tensor or jax.Array in/out)."""
    from ..core.op import apply_op
    from ..core.tensor import Tensor

    def raw(qv, kv, vv):
        b, tq, h, d = qv.shape
        tk = kv.shape[1]
        q3 = jnp.transpose(qv, (0, 2, 1, 3)).reshape(b * h, tq, d)
        k3 = jnp.transpose(kv, (0, 2, 1, 3)).reshape(b * h, tk, d)
        v3 = jnp.transpose(vv, (0, 2, 1, 3)).reshape(b * h, tk, d)
        o3 = flash_attention_bhtd(q3, k3, v3, causal=causal, scale=scale)
        return jnp.transpose(o3.reshape(b, h, tq, d), (0, 2, 1, 3))

    if isinstance(q, Tensor):
        return apply_op(raw, "flash_attention", (q, k, v), {})
    return raw(q, k, v)
