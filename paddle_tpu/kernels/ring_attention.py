"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has NO sequence parallelism (SURVEY §5.7: repo-wide grep for
ring_attention/context_parallel/ulysses = zero hits); long sequences are
handled only via recompute + TP/PP memory sharing.  This module is the
TPU-idiomatic extension the rebuild adds (flagged as beyond-reference):

* **Ring attention** — the sequence is sharded over mesh axis ``sep``; K/V
  chunks rotate around the ring via `lax.ppermute` while each device keeps a
  streaming-softmax accumulator (m, l, acc).  Memory per device is
  O(T_local²) for scores instead of O(T_global²), and the per-step ppermute
  rides ICI while the MXU chews on the current chunk.  Equivalent math to
  blockwise attention (Liu et al. ring attention; public JAX versions exist —
  this one is written against this repo's [B, T, H, D] paddle layout).
* **Ulysses** — all-to-all swaps the sharded axis from sequence→heads, runs
  dense/flash attention on the full sequence with H/sep heads per device,
  and swaps back.  Cheaper collectives than the ring when H ≥ sep and
  sequence fits; the ring wins at extreme lengths.

Both are differentiable through plain jax autodiff (ppermute/all_to_all have
transfer-transpose rules), so they compose with jax.grad / value_and_grad in
the SPMD train step with no custom VJP.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _to_bhtd(x):
    return jnp.swapaxes(x, 1, 2)  # [B,T,H,D] -> [B,H,T,D]


def ring_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                   scale: float | None = None):
    """Blockwise ring attention over a sharded sequence axis.

    Args are the LOCAL shards, paddle layout [B, T_local, H, D]; must be
    called inside `shard_map` (or pjit-manual) with `axis_name` bound.
    Token order is contiguous: ring rank i holds global positions
    [i*T_local, (i+1)*T_local).  Returns the local output shard [B,T,H,D].

    Causal note: contiguous layout means later ring ranks do more work in the
    causal case (the striped/zigzag layout rebalances this; kept simple and
    documented as future work).
    """
    S = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)

    # K/V stay in the input dtype through the ppermutes (bf16 halves the ICI
    # bytes per ring step); only scores/accumulators run in f32
    qh = _to_bhtd(q)                               # [B,H,T,D]
    kh = _to_bhtd(k)
    vh = _to_bhtd(v)
    B, H, T, D = qh.shape

    q_pos = idx * T + jnp.arange(T)                # global query positions

    perm = [(j, (j + 1) % S) for j in range(S)]

    def scores_for(src, kc):
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kc,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = src * T + jnp.arange(T)
            allowed = k_pos[None, :] <= q_pos[:, None]     # [T, T]
            s = jnp.where(allowed[None, None], s, _NEG_INF)
        return s

    # iteration 0 peeled: the local diagonal chunk needs no ppermute and
    # seeds the streaming-softmax accumulators (also gives them the right
    # varying-manual-axes type for the loop carry)
    scores = scores_for(idx, kh)
    m = scores.max(axis=-1)
    p = jnp.exp(scores - m[..., None])
    l = p.sum(axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, vh,
                     preferred_element_type=jnp.float32)

    def step(s, carry):
        acc, m, l, kc, vc = carry
        # permute at loop top so the final rotation isn't computed and thrown
        # away; after s right-shifts this device holds the chunk that
        # originated on ring rank (idx - s) mod S
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        scores = scores_for((idx - s) % S, kc)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new, kc, vc

    acc, m, l, _, _ = lax.fori_loop(1, S, step, (acc, m, l, kh, vh))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)   # [B,T,H,D]


def ulysses_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                      scale: float | None = None, inner=None):
    """DeepSpeed-Ulysses style: all-to-all seq→heads, full-seq attention,
    all-to-all heads→seq.  Local shards [B, T_local, H, D], H % sep == 0.
    `inner(q,k,v,causal,scale) -> out` runs the per-device full-sequence
    attention (defaults to a dense reference; a flash kernel slots in)."""
    S = lax.psum(1, axis_name)
    if q.shape[2] % S:
        raise ValueError(f"num_heads {q.shape[2]} not divisible by "
                         f"sep={S} for ulysses all-to-all")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)

    def swap_in(x):   # [B, T/S, H, D] -> [B, T, H/S, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def swap_out(x):  # [B, T, H/S, D] -> [B, T/S, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = swap_in(q), swap_in(k), swap_in(v)
    if inner is None:
        out = _dense_attention(qg, kg, vg, causal, scale)
    else:
        out = inner(qg, kg, vg, causal, scale)
    return swap_out(out)


def _dense_attention(q, k, v, causal, scale):
    """Reference full-sequence attention, [B,T,H,D] layout (delegates to the
    single dense implementation in nn.functional.attention)."""
    from ..nn.functional.attention import _sdpa_ref
    return _sdpa_ref(q, k, v, None, 0.0, causal, scale, False)
