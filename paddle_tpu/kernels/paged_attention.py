"""Paged decode-attention as a Pallas TPU kernel (ISSUE 19) — the fused
read for the serving engine's paged KV pool (docs/serving.md "Paged KV").

The XLA paged read gathers every slot's pages into a ``[B, L_virt, heads,
head_dim]`` temp per layer and (int8 pools) dequantizes as a separate
pass, so HBM streams f32 gather bytes regardless of what the pool stores.
This kernel walks the page table directly instead:

* the per-slot int32 page table and lengths ride as **scalar-prefetch**
  operands (SMEM, available before the body runs), so each grid step can
  compute which physical page it needs and DMA exactly that
  ``[page_size, heads, head_dim]`` page from HBM into VMEM — no
  ``[B, L_virt, ...]`` gather temp exists anywhere;
* int8 pools dequantize **inside the page read** (``q_i8 * scale`` on the
  VMEM tile), so HBM streams the int8 pool bytes — the stored-bytes
  ratio becomes the streamed-bytes ratio;
* pages past a row's live span (``start + W``) are skipped entirely:
  bytes scale with the tokens actually resident, not the table width.

Grid ``(B, 2, n_pt)``, phases sequential per row (``arbitrary``):

* phase 0 streams the row's K pages and writes masked scaled scores into
  a per-row VMEM scores scratch (position ``p`` attends to query ``j``
  iff ``p <= start + j`` — the causal-within-span + validity mask of
  models/gpt.py's paged branch, bit for bit);
* phase 1 softmaxes the **whole** scores row in one shot (same f32
  exp/sum shape as ``_sdpa_ref``'s ``jax.nn.softmax``, which keeps
  greedy argmax aligned with the XLA path), then streams the row's V
  pages and accumulates ``probs @ V`` per page.

Two phases read K then V once each — the same HBM traffic as a one-pass
online-softmax kernel, without the rescaling carry.  Sentinel table
entries (``>= num_pages``) clamp to the last physical page exactly like
the XLA gather's ``pt_safe`` clip; parked rows (``start == L_virt``)
produce the same never-read garbage either way.

Correctness gates through interpret mode on CPU (auto-detected, or
``PADDLE_TPU_PALLAS_INTERPRET=1`` / :func:`use_interpret_mode`); the
serving engine routes decode through here only inside
:func:`decode_kernel_scope` (``Engine(decode_kernel="pallas")``), the
same trace-local mechanism the multi-LoRA adapter path uses.
"""
from __future__ import annotations

import contextlib
import functools
import math
import os
import threading

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# this jax exposes the compiler-params dataclass under its older name
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")

# interpret-mode resolution: None = auto (env var, else non-TPU backend);
# use_interpret_mode() pins it for tests/debugging
_INTERPRET = None


def use_interpret_mode(flag):
    """Pin interpret mode on/off, or ``None`` to restore auto-detect."""
    global _INTERPRET
    _INTERPRET = None if flag is None else bool(flag)


def _interpret_now() -> bool:
    if _INTERPRET is not None:
        return _INTERPRET
    env = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "")
    if env:
        return env not in ("0", "false", "False")
    return jax.default_backend() != "tpu"


# -- trace-local routing scope ------------------------------------------------
#
# The engine enters this scope inside its decode jit (and only there), so
# the model's paged cache branch routes its attention read through the
# kernel for exactly that program — prefill/tail-prefill keep the XLA
# read, and the decode signature count stays at ONE per config (the scope
# is a trace-time routing decision, not an operand).

_TLS = threading.local()


@contextlib.contextmanager
def decode_kernel_scope():
    prev = getattr(_TLS, "active", False)
    _TLS.active = True
    try:
        yield
    finally:
        _TLS.active = prev


def active() -> bool:
    """True while tracing inside :func:`decode_kernel_scope`."""
    return getattr(_TLS, "active", False)


# -- analytic cost registration (observability/perfscope.py) ------------------
#
# XLA's cost_analysis books a pallas custom call at zero flops/bytes, so
# the kernel registers its own analytic numbers once per shape signature
# — the per-program roofline (PR 14) then attributes kernel dispatches
# the same way it does the jit programs around them.

_COSTS_BOOKED = set()
PERFSCOPE_PROGRAM = "kernels.paged_attention"


def _book_cost(B, W, H, D, P, n_pt, quant):
    key = f"B{B}xW{W}xH{H}xD{D}/P{P}x{n_pt}" + ("/int8" if quant else "/f32")
    if key in _COSTS_BOOKED:
        return
    _COSTS_BOOKED.add(key)
    virt = n_pt * P
    # QK^T + probs@V: 2 matmuls of [W, virt] x [virt, D] per head per row
    flops = 4.0 * B * H * W * virt * D
    esize = 1 if quant else 4
    pool_bytes = 2.0 * B * virt * H * D * esize      # K + V pages streamed
    if quant:
        pool_bytes += 2.0 * B * virt * 4             # f32 scale sidecars
    io_bytes = 2.0 * B * W * H * D * 4               # q in + out
    try:
        from ..observability import perfscope
        perfscope.register_cost(PERFSCOPE_PROGRAM, key,
                                {"flops": flops,
                                 "bytes accessed": pool_bytes + io_bytes})
    except Exception:  # noqa: BLE001 — observability must never break math
        pass


# -- kernel body --------------------------------------------------------------

def _decode_kernel(pt_ref, len_ref, q_ref, k_hbm, v_hbm, *rest,
                   P, n_pt, NP, W, H, D, scale, quant):
    if quant:
        ks_hbm, vs_hbm, o_ref, s_ref, acc_ref, kv_vmem, sc_vmem, sem, \
            ssem = rest
    else:
        o_ref, s_ref, acc_ref, kv_vmem, sem = rest
    b, ph, i = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    start = len_ref[b]
    # page i holds positions [i*P, (i+1)*P): live for this row iff any of
    # them is attendable by the widest query (start + W - 1)
    needed = (i * P) < (start + W)
    # sentinel entries (>= NP) clamp to the last physical page — same
    # bytes the XLA gather's pt_safe clip reads, masked out below
    pid = jnp.minimum(pt_ref[b, i], NP - 1)

    def _page(hbm_ref, sc_ref):
        """DMA one K/V page (+ its scale sidecar) and dequantize."""
        cp = pltpu.make_async_copy(hbm_ref.at[pid], kv_vmem, sem)
        cp.start()
        if quant:
            cs = pltpu.make_async_copy(sc_ref.at[pid], sc_vmem, ssem)
            cs.start()
            cp.wait()
            cs.wait()
            return kv_vmem[...].astype(jnp.float32) * \
                sc_vmem[...][:, None, None]
        cp.wait()
        return kv_vmem[...].astype(jnp.float32)

    @pl.when((ph == 0) & needed)
    def _scores():
        kh = jnp.transpose(_page(k_hbm, ks_hbm if quant else None),
                           (1, 0, 2))                        # [H, P, D]
        qh = jnp.transpose(q_ref[0].astype(jnp.float32), (1, 0, 2))
        s = jax.lax.dot_general(
            qh, kh, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * jnp.float32(scale)
        col = i * P + jax.lax.broadcasted_iota(jnp.int32, (W, P), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (W, P), 0)
        s = jnp.where((col <= start + row)[None], s, jnp.float32(_NEG_INF))
        s_ref[:, :, pl.ds(i * P, P)] = s

    @pl.when((ph == 0) & jnp.logical_not(needed))
    def _dead():
        # no DMA for pages past the live span: their scores are -inf, so
        # phase 1's probs underflow to exactly 0 and the page is skipped
        s_ref[:, :, pl.ds(i * P, P)] = jnp.full((H, W, P), _NEG_INF,
                                                jnp.float32)

    @pl.when((ph == 1) & (i == 0))
    def _softmax():
        # whole-row softmax in one shot (the _sdpa_ref f32 exp/sum shape);
        # probs overwrite the scores scratch in place
        s = s_ref[...]
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        s_ref[...] = p / jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when((ph == 1) & needed)
    def _weighted():
        vh = jnp.transpose(_page(v_hbm, vs_hbm if quant else None),
                           (1, 0, 2))                        # [H, P, D]
        pr = s_ref[:, :, pl.ds(i * P, P)]                    # [H, W, P]
        acc_ref[...] += jax.lax.dot_general(
            pr, vh, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when((ph == 1) & (i == n_pt - 1))
    def _finish():
        o_ref[0] = jnp.transpose(acc_ref[...], (1, 0, 2)).astype(o_ref.dtype)


# -- public API ---------------------------------------------------------------

def paged_decode_attention(q, k_pages, v_pages, page_table, lengths,
                           k_scale=None, v_scale=None, scale=None):
    """Fused paged attention read for per-slot decode.

    Args:
        q: ``[B, W, heads, head_dim]`` queries (W=1 plain decode, W=k
            speculative verify), already holding the step's new
            positions ``start .. start+W-1``.
        k_pages / v_pages: ``[num_pages, page_size, heads, head_dim]``
            pools, f32 (model dtype) or int8 — **post-write**: the
            step's scatter must already have landed so the read attends
            over the new positions exactly like the XLA path.
        page_table: ``[B, n_pt]`` int32; entries ``>= num_pages`` are
            sentinels (parked / unallocated).
        lengths: ``[B]`` int32 per-row start positions (parked rows sit
            at ``n_pt * page_size``).
        k_scale / v_scale: ``[num_pages, page_size]`` f32 absmax scales,
            required iff the pools are int8 (serving/kv_quant.py).

    Returns:
        ``[B, W, heads, head_dim]`` attention output in ``q.dtype``.
    """
    B, W, H, D = q.shape
    NP, P = k_pages.shape[0], k_pages.shape[1]
    n_pt = page_table.shape[1]
    virt = n_pt * P
    quant = k_pages.dtype == jnp.int8
    if quant != (k_scale is not None):
        raise ValueError("int8 pools need k_scale/v_scale and f32 pools "
                         f"must not pass them (pool {k_pages.dtype}, "
                         f"k_scale={'set' if k_scale is not None else None})")
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    _book_cost(B, W, H, D, P, n_pt, quant)

    qmap = lambda b, ph, i, *_: (b, ph * 0, i * 0, ph * 0)   # noqa: E731
    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    in_specs = [pl.BlockSpec((1, W, H, D), qmap), any_spec, any_spec]
    operands = [jnp.asarray(page_table, jnp.int32),
                jnp.asarray(lengths, jnp.int32), q, k_pages, v_pages]
    scratch = [
        pltpu.VMEM((H, W, virt), jnp.float32),     # scores, then probs
        pltpu.VMEM((H, W, D), jnp.float32),        # output accumulator
        pltpu.VMEM((P, H, D), k_pages.dtype),      # the in-flight page
        pltpu.SemaphoreType.DMA,
    ]
    if quant:
        in_specs += [any_spec, any_spec]
        operands += [k_scale, v_scale]
        scratch.insert(3, pltpu.VMEM((P,), jnp.float32))
        scratch.append(pltpu.SemaphoreType.DMA)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, 2, n_pt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, W, H, D), qmap),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _decode_kernel, P=P, n_pt=n_pt, NP=NP, W=W, H=H, D=D,
        scale=float(scale), quant=quant)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, W, H, D), q.dtype),
        compiler_params=_CompilerParams(
            # rows are independent (parallel); the phase/page dims carry
            # the scores scratch and must run sequentially per row
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret_now(),
    )(*operands)
