"""DevicePrefetcher — the overlapped input pipeline (ISSUE 4 tentpole).

The DataLoader hands out numpy batches; before this module every consumer
serialized host batch prep, the H2D transfer and the device step into one
chain (the transfer happened inside the step call, so the device waited on
the host between steps — ~10 ms per dispatch through the remote tunnel,
docs/PERF.md).  ``DevicePrefetcher`` wraps any DataLoader/iterable and
keeps up to ``depth`` batches device-resident ahead of the consumer: a
background thread pulls host batches and issues ``jax.device_put`` (or
``mesh.put_global`` with the SPMD ``batch_spec`` sharding when a mesh is
given), so batch *k+1* is already on device while step *k* runs.

Contracts:

* **Bounded.**  At most ``depth`` batches sit in the buffer; the producer
  holds at most one more in flight, so the source is never more than
  ``depth + 1`` batches ahead of the consumer.
* **Clean end/err.**  Source exhaustion becomes a normal ``StopIteration``;
  a producer-side exception is re-raised in the consumer at the position
  it occurred.
* **No leaked threads.**  Dropping the iterator (``break``, GC) or calling
  ``close()`` stops the producer; its enqueue loop polls a stop event, so
  it can never block forever on a full buffer.
* **Zero syncs when warm.**  A warm buffer costs one ``Queue.get_nowait``
  per batch — no device sync, no new jit signature (the consumer-side
  train steps recognize the already-sharded arrays and skip re-transfer).

Telemetry: the always-on flight recorder gets a ``pipeline_stall`` event
whenever the consumer finds the buffer empty after warmup (the device is
about to wait on the host); with ``PADDLE_TPU_TELEMETRY=1`` the metrics
registry additionally carries the buffer-occupancy gauge and the
``host_input_wait_seconds`` counter (observability/steps.py).  ``stats()``
exposes the same numbers as plain floats for bench legs.
"""
from __future__ import annotations

import queue as queue_mod
import threading
import time

import numpy as np

from ..core.tensor import Tensor

# sentinel: the source is exhausted (producer -> consumer)
_END = object()


class _Failure:
    """Producer-side exception carried through the queue."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


def _tree_put(obj, put):
    """Transfer every array leaf of a batch nest, keeping the container
    shape; leaves come back as Tensors over device arrays so both the hapi
    eager path and the SPMD step unwrap them without another copy."""
    if isinstance(obj, Tensor):
        return Tensor(put(obj._value), _internal=True)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_put(v, put) for v in obj)
    if isinstance(obj, dict):
        return {k: _tree_put(v, put) for k, v in obj.items()}
    if isinstance(obj, (np.ndarray, np.generic)) or hasattr(obj, "shape"):
        return Tensor(put(obj), _internal=True)
    return obj


def _tree_nbytes(obj) -> int:
    """Device bytes of a batch nest's array leaves (shape/dtype metadata
    only — never touches data, never syncs)."""
    if isinstance(obj, Tensor):
        obj = obj._value
    if isinstance(obj, (list, tuple)):
        return sum(_tree_nbytes(v) for v in obj)
    if isinstance(obj, dict):
        return sum(_tree_nbytes(v) for v in obj.values())
    shape = getattr(obj, "shape", None)
    dtype = getattr(obj, "dtype", None)
    if shape is None or dtype is None:
        return 0
    itemsize = getattr(dtype, "itemsize", None) or np.dtype(dtype).itemsize
    return int(np.prod(shape)) * int(itemsize)


class _PrefetchIter:
    """One epoch: a producer thread + a bounded queue.  Created fresh per
    ``iter(DevicePrefetcher)`` so epoch loops restart the pipeline."""

    def __init__(self, owner: "DevicePrefetcher", source):
        self._owner = owner
        self._q: queue_mod.Queue = queue_mod.Queue(maxsize=owner.depth)
        self._stop = threading.Event()
        self._warm = False
        self._done = False
        # HBM-ledger row: device bytes sitting in this buffer (batches
        # transferred but not yet consumed) declare their owner, so a
        # /debug/memory snapshot can name prefetch-held HBM
        from ..observability import perfscope
        self._ledger = perfscope.ledger().register(
            "prefetch", 0, detail=f"DevicePrefetcher buffer ({owner.name})")
        self._thread = threading.Thread(
            target=self._produce, args=(source,), daemon=True,
            name=f"prefetch-{owner.name}")
        self._thread.start()

    # -- producer ------------------------------------------------------------
    def _produce(self, source):
        put = self._owner._put
        try:
            for batch in source:
                if self._stop.is_set():
                    return
                dev = _tree_put(batch, put)
                if not self._enqueue(dev):
                    return
            self._enqueue(_END)
        except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
            self._enqueue(_Failure(e))

    def _enqueue(self, item) -> bool:
        # bounded put that can always be woken by close(): never block
        # indefinitely on a full buffer the consumer abandoned
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
            except queue_mod.Full:
                continue
            self._ledger.add(_tree_nbytes(item))
            self._owner._note_depth(self._q.qsize())
            return True
        return False

    # -- consumer ------------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        owner = self._owner
        try:
            item = self._q.get_nowait()
        except queue_mod.Empty:
            # the train loop is about to wait on the host.  After warmup
            # that is a pipeline stall (producer slower than the device);
            # the cold first batch is expected and only counts as wait.
            stalled = self._warm
            t0 = time.perf_counter()
            item = self._blocking_get()
            owner._note_wait(time.perf_counter() - t0, stalled=stalled)
        self._warm = True
        self._ledger.add(-_tree_nbytes(item))
        owner._note_depth(self._q.qsize())
        if item is _END:
            self.close()
            raise StopIteration
        if isinstance(item, _Failure):
            self.close()
            raise item.exc
        owner._note_batch()
        return item

    def _blocking_get(self):
        while True:
            try:
                return self._q.get(timeout=1.0)
            except queue_mod.Empty:
                if not self._thread.is_alive():
                    # the Failure/_END protocol covers every normal exit;
                    # this guards against the producer dying unenqueued
                    raise RuntimeError(
                        "DevicePrefetcher producer thread died without "
                        "delivering a result")

    def close(self):
        """Stop the producer and release the buffer.  Idempotent; called on
        normal exhaustion, error, early exit and GC."""
        self._done = True
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._ledger.release()     # buffered batches die with the iterator

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class DevicePrefetcher:
    """Wrap a DataLoader/iterable; yield device-resident batches ``depth``
    ahead of the consumer.

    With ``mesh`` given, every array leaf is placed with the SPMD
    ``batch_spec`` sharding (leading dim over the data axes) so the train
    step's ``shard_batch`` recognizes it and skips the re-transfer;
    ``stacked=True`` uses the ``run_steps`` layout instead (replicated
    leading K dim, data axes on dim 1).  Without a mesh, leaves go through
    plain ``jax.device_put``.

    Re-iterable: each ``iter()`` starts a fresh producer over
    ``iter(data)``; ``stats()`` aggregates across epochs.
    """

    def __init__(self, data, depth: int = 2, mesh=None,
                 stacked: bool = False, name: str = "prefetch"):
        self.data = data
        self.depth = max(1, int(depth))
        self.mesh = mesh
        self.stacked = bool(stacked)
        self.name = name
        self._last_iter: _PrefetchIter | None = None
        self._lock = threading.Lock()
        # plain-float stats, always on (bench reads them without telemetry)
        self.batches = 0
        self.wait_seconds = 0.0
        self.stalls = 0

    # -- placement -----------------------------------------------------------
    def _put(self, v):
        import jax
        if self.mesh is None:
            return jax.device_put(v)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..distributed import mesh as mesh_mod
        from ..distributed.spmd import batch_spec
        ndim = int(np.ndim(v))
        if ndim == 0:
            spec = P()
        elif self.stacked:
            spec = P(None, *tuple(batch_spec(self.mesh, ndim - 1)))
        else:
            spec = batch_spec(self.mesh, ndim)
        return mesh_mod.put_global(v, NamedSharding(self.mesh, spec))

    # -- iteration -----------------------------------------------------------
    def __iter__(self):
        obs = self._obs()
        if obs.enabled():
            # pre-register the series at 0 so an exporter can tell "no
            # wait" (healthy overlap) from "not instrumented"
            obs.steps.record_input_wait(0.0, fn=self.name)
            obs.steps.set_prefetch_depth(0, fn=self.name)
        it = _PrefetchIter(self, iter(self.data))
        with self._lock:
            prev, self._last_iter = self._last_iter, it
        if prev is not None:
            prev.close()
        return it

    def __len__(self):
        return len(self.data)

    def close(self):
        with self._lock:
            it, self._last_iter = self._last_iter, None
        if it is not None:
            it.close()

    def stats(self) -> dict:
        # under the same lock the telemetry sinks take, so a reader
        # polling from another thread gets a consistent snapshot
        with self._lock:
            return {"batches": self.batches, "depth": self.depth,
                    "wait_seconds": self.wait_seconds,
                    "stalls": self.stalls}

    # -- telemetry sinks (called from both threads) --------------------------
    @staticmethod
    def _obs():
        from .. import observability
        return observability

    def _note_depth(self, qsize: int):
        obs = self._obs()
        if obs.enabled():
            obs.steps.set_prefetch_depth(qsize, fn=self.name)

    def _note_wait(self, seconds: float, stalled: bool):
        with self._lock:
            self.wait_seconds += seconds
            if stalled:
                self.stalls += 1
        obs = self._obs()
        if stalled:
            # always-on flight event: the device waited on the host
            obs.flight.record("pipeline_stall", self.name,
                              waited_ms=round(seconds * 1e3, 3),
                              depth=self.depth)
        if obs.enabled():
            obs.steps.record_input_wait(seconds, fn=self.name)
            if stalled:
                obs.steps.record_pipeline_stall(fn=self.name)

    def _note_batch(self):
        with self._lock:
            self.batches += 1
        obs = self._obs()
        if obs.enabled():
            obs.steps.record_prefetch_batch(fn=self.name)
