"""paddle.io parity surface."""
from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split,
)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    BatchSampler, DistributedBatchSampler,
)
from .dataloader import DataLoader, get_worker_info, default_collate_fn  # noqa: F401
from .prefetch import DevicePrefetcher  # noqa: F401
