"""Shared-memory batch channel for DataLoader workers — Python side of
csrc/shm_ring.cpp (reference: shared-memory tensor transfer in
fluid/dataloader/dataloader_iter.py + use_shared_memory flag).

Numpy batches cross the process boundary as raw bytes in a POSIX shm ring:
no pickle for array payloads; a compact header carries dtype/shape.  Falls
back transparently (`available()` False) when the toolchain is missing.
"""
from __future__ import annotations

import ctypes
import os
import pickle
import struct
import uuid

import numpy as np

_LIB = None
_LIB_ERR = None


def _build():
    from ..utils.native_build import build_native_lib

    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "csrc", "shm_ring.cpp")
    return build_native_lib(src, "libshm_ring.so", extra_flags=("-lrt",))


def _lib():
    global _LIB, _LIB_ERR
    if _LIB is not None or _LIB_ERR is not None:
        return _LIB
    try:
        lib = ctypes.CDLL(_build())
        lib.shmring_create.restype = ctypes.c_void_p
        lib.shmring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                       ctypes.c_int]
        lib.shmring_open.restype = ctypes.c_void_p
        lib.shmring_open.argtypes = [ctypes.c_char_p]
        lib.shmring_write.restype = ctypes.c_int
        lib.shmring_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64, ctypes.c_int]
        lib.shmring_read.restype = ctypes.c_longlong
        lib.shmring_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_uint64, ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_uint64)]
        lib.shmring_close.argtypes = [ctypes.c_void_p]
        lib.shmring_free.argtypes = [ctypes.c_void_p]
        _LIB = lib
    except Exception as e:  # pragma: no cover
        _LIB_ERR = e
    return _LIB


def available() -> bool:
    return _lib() is not None


# -- batch codec -------------------------------------------------------------
# message: u64 bid | u8 kind | payload
#   kind 0 = tuple of arrays, 2 = list of arrays, 3 = single bare array
#   kind 1 = pickled python object (exceptions, odd collations)
#   arrays payload: u32 count | per array: u16 dtype_len, dtype, u8 ndim,
#                   u64*ndim shape, u64 nbytes, raw
def encode_batch(bid: int, batch) -> bytes:
    if isinstance(batch, np.ndarray):
        kind, arrays = 3, [batch]
    elif isinstance(batch, list):
        kind, arrays = 2, batch
    elif isinstance(batch, tuple):
        kind, arrays = 0, list(batch)
    else:
        kind, arrays = 1, None
    if kind != 1 and all(isinstance(a, np.ndarray) and a.dtype != object
                         for a in arrays):
        parts = [struct.pack("<QB", bid, kind)]
        parts.append(struct.pack("<I", len(arrays)))
        for a in arrays:
            a = np.ascontiguousarray(a)
            dt = a.dtype.str.encode()
            parts.append(struct.pack("<H", len(dt)))
            parts.append(dt)
            parts.append(struct.pack("<B", a.ndim))
            parts.append(struct.pack(f"<{a.ndim}Q", *a.shape) if a.ndim
                         else b"")
            parts.append(struct.pack("<Q", a.nbytes))
            parts.append(a.tobytes())
        return b"".join(parts)
    return struct.pack("<QB", bid, 1) + pickle.dumps(batch, protocol=4)


def decode_batch(data: bytes):
    bid, kind = struct.unpack_from("<QB", data, 0)
    off = 9
    if kind == 1:
        return bid, pickle.loads(data[off:])
    (count,) = struct.unpack_from("<I", data, off)
    off += 4
    arrays = []
    for _ in range(count):
        (dlen,) = struct.unpack_from("<H", data, off)
        off += 2
        dtype = np.dtype(data[off:off + dlen].decode())
        off += dlen
        (ndim,) = struct.unpack_from("<B", data, off)
        off += 1
        shape = struct.unpack_from(f"<{ndim}Q", data, off) if ndim else ()
        off += 8 * ndim
        (nbytes,) = struct.unpack_from("<Q", data, off)
        off += 8
        arr = np.frombuffer(data, dtype=dtype, count=nbytes // dtype.itemsize,
                            offset=off).reshape(shape)
        off += nbytes
        arrays.append(arr)
    if kind == 3:
        return bid, arrays[0]
    return bid, (arrays if kind == 2 else tuple(arrays))


class ShmQueue:
    """One-direction message queue over the native ring."""

    def __init__(self, capacity=64 << 20, name=None, create=True,
                 linger=False):
        """linger=False (default) unlinks the shm name right after creation:
        the segment lives exactly as long as its (fork-inherited) mappings,
        so crashed runs can never leak /dev/shm memory.  linger=True keeps
        the name so unrelated processes can `open_peer()` by name — the
        creator must then call free()."""
        lib = _lib()
        if lib is None:
            raise RuntimeError(f"shm ring unavailable: {_LIB_ERR}")
        self.name = name or f"/pt_ring_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        self._linger = linger
        if create:
            self._h = lib.shmring_create(self.name.encode(), capacity,
                                         1 if linger else 0)
        else:
            self._h = lib.shmring_open(self.name.encode())
        if not self._h:
            raise RuntimeError(f"shm ring {self.name} setup failed")
        self._closed = False

    def open_peer(self) -> "ShmQueue":
        """Handle for a non-forked peer (reopen by name; needs linger=True).
        Forked children simply inherit this object's mapping."""
        if self._linger is False:
            raise RuntimeError(
                "open_peer needs ShmQueue(linger=True); forked children "
                "inherit the mapping and don't need it")
        return ShmQueue(name=self.name, create=False)

    def put(self, data: bytes, timeout_ms=-1):
        if self._closed or not self._h:
            raise BrokenPipeError("shm ring closed")
        rc = _lib().shmring_write(self._h, data, len(data), timeout_ms)
        if rc == -3:
            raise ValueError(
                f"message of {len(data)} bytes exceeds ring capacity; raise "
                "DataLoader(shm_ring_capacity=...) or shrink the batch")
        if rc == -2:
            raise TimeoutError("shm ring write timed out")
        if rc != 0:
            raise BrokenPipeError("shm ring closed")

    def get(self, timeout_ms=-1) -> bytes:
        if self._closed or not self._h:
            raise BrokenPipeError("shm ring closed")
        cap = 1 << 20
        need = ctypes.c_uint64(0)
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = _lib().shmring_read(self._h, buf, cap, timeout_ms,
                                    ctypes.byref(need))
            if n == -3:
                cap = int(need.value) + 16
                continue
            if n == -2:
                raise TimeoutError("shm ring read timed out")
            if n < 0:
                raise BrokenPipeError("shm ring closed")
            return ctypes.string_at(buf, n)

    def close(self):
        if not self._closed and self._h:
            _lib().shmring_close(self._h)
            self._closed = True

    def free(self):
        if self._h:
            _lib().shmring_free(self._h)
            self._h = None
