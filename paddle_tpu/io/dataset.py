"""Datasets (reference: python/paddle/io/ → fluid/dataloader/dataset.py)."""
from __future__ import annotations

import bisect

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        assert all(t.shape[0] == tensors[0].shape[0] for t in tensors)
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(ds) for ds in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        offset = idx - (self.cumulative_sizes[ds_idx - 1] if ds_idx else 0)
        return self.datasets[ds_idx][offset]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths) and abs(sum(lengths) - 1.0) < 1e-6:
        counts = [int(np.floor(total * l)) for l in lengths]
        for i in range(total - sum(counts)):
            counts[i % len(counts)] += 1
        lengths = counts
    if sum(lengths) != total:
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(total)
    out, start = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[start:start + l].tolist()))
        start += l
    return out
