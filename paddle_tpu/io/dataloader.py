"""DataLoader (reference: fluid/dataloader/dataloader_iter.py:342
_DataLoaderIterMultiProcess — worker procs + shared memory + prefetch;
fluid/reader.py facade).

TPU-side note: feeding chips is a host job.  The multiprocess path uses
worker processes with pickled numpy batches over queues plus a prefetch
depth (≈ buffered_reader.cc double-buffering); batches stay numpy so the
jitted train step controls the single H2D transfer.
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue as queue_mod
import threading
import traceback

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return Tensor(np.stack([s.numpy() for s in batch]))
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn(list(items))
                            for items in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


def _to_tensor_nest(obj, return_list):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_tensor_nest(v, return_list) for v in obj)
    if isinstance(obj, dict):
        return {k: _to_tensor_nest(v, return_list) for k, v in obj.items()}
    return obj


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id,
                 num_workers, seed, worker_init_fn=None):
    global _worker_info
    _worker_info = _WorkerInfo(worker_id, num_workers, dataset, seed)
    np.random.seed((seed + worker_id) % (2 ** 32))
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    # flight-recorder spans live in THIS process's ring (fork copy): a
    # worker crash dump shows whether it died starving (get wait) or
    # blocked on a full ring (put wait)
    from ..observability import trace as _trace
    is_iterable = isinstance(dataset, IterableDataset)
    it = iter(dataset) if is_iterable else None
    while True:
        with _trace.span("dataloader.worker_get", worker=worker_id):
            task = index_queue.get()
        if task is None:
            break
        batch_id, indices = task
        try:
            if is_iterable:
                samples = list(itertools.islice(it, len(indices)))
                if not samples:
                    data_queue.put((batch_id, StopIteration(), None))
                    continue
            else:
                samples = [dataset[i] for i in indices]
            batch = collate_fn(samples)
            with _trace.span("dataloader.worker_put", worker=worker_id,
                             batch_id=batch_id):
                data_queue.put((batch_id, None, batch))
        except BrokenPipeError:  # shm ring closed by parent shutdown
            break
        except Exception:  # noqa: BLE001
            try:
                data_queue.put((batch_id,
                                RuntimeError(traceback.format_exc()), None))
            except BrokenPipeError:
                break


class _SingleProcessIter:
    def __iter__(self):
        return self

    def __init__(self, loader):
        self.loader = loader
        ds = loader.dataset
        if isinstance(ds, IterableDataset):
            self._it = iter(ds)
            self._batches = None
        else:
            self._it = None
            self._batches = iter(loader.batch_sampler)

    def __next__(self):
        loader = self.loader
        if self._it is not None:
            samples = list(itertools.islice(self._it, loader.batch_size or 1))
            if not samples:
                raise StopIteration
        else:
            indices = next(self._batches)
            samples = [loader.dataset[i] for i in indices]
        batch = loader.collate_fn(samples)
        return _to_tensor_nest(batch, loader.return_list)


class _ShmDataQueue:
    """mp.Queue-compatible (put/get of (bid, err, batch)) over the native
    shared-memory ring (csrc/shm_ring.cpp): numpy batch payloads cross the
    process boundary without pickling — the reference's shared-memory tensor
    path (use_shared_memory, dataloader_iter.py)."""

    _EXC_KEY = "__pt_exc__"

    def __init__(self, capacity=64 << 20):
        from .shm_channel import ShmQueue
        self._q = ShmQueue(capacity=capacity)

    def put(self, item):
        from .shm_channel import encode_batch
        bid, err, batch = item
        if err is None:
            # encode_batch keeps the container (tuple/list/bare array) and
            # falls back to pickle for anything non-array
            self._q.put(encode_batch(bid, batch))
        else:
            self._q.put(encode_batch(bid, {self._EXC_KEY: err,
                                           "batch": batch}))

    def get(self):
        from .shm_channel import decode_batch
        bid, payload = decode_batch(self._q.get())
        if isinstance(payload, dict) and self._EXC_KEY in payload:
            return bid, payload[self._EXC_KEY], payload.get("batch")
        return bid, None, payload

    def close(self):
        self._q.close()
        self._q.free()


class _MultiProcessIter:
    def __init__(self, loader):
        self.loader = loader
        self.num_workers = loader.num_workers
        ctx = mp.get_context("fork")
        self.index_queues = [ctx.Queue() for _ in range(self.num_workers)]
        self.data_queue = None
        if loader.use_shared_memory:
            from . import shm_channel
            if shm_channel.available():
                self.data_queue = _ShmDataQueue(
                    capacity=loader.shm_ring_capacity)
        if self.data_queue is None:
            self.data_queue = ctx.Queue()
        seed = np.random.randint(0, 2 ** 31)
        self.workers = []
        for wid in range(self.num_workers):
            w = ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self.index_queues[wid], self.data_queue,
                      loader.collate_fn, wid, self.num_workers, seed,
                      loader.worker_init_fn),
                daemon=True)
            w.start()
            self.workers.append(w)
        if isinstance(loader.dataset, IterableDataset):
            bs = loader.batch_size or 1
            self._batches = iter(lambda: list(range(bs)), None)  # endless
        else:
            self._batches = iter(loader.batch_sampler)
        self._send_idx = 0
        self._recv_idx = 0
        self._reorder = {}
        self._outstanding = 0
        self._exhausted = False
        for _ in range(loader.prefetch_factor * self.num_workers):
            self._dispatch()

    def _dispatch(self):
        if self._exhausted:
            return
        try:
            indices = next(self._batches)
        except StopIteration:
            self._exhausted = True
            return
        wid = self._send_idx % self.num_workers
        self.index_queues[wid].put((self._send_idx, indices))
        self._send_idx += 1
        self._outstanding += 1

    def __next__(self):
        while True:
            if self._outstanding == 0:
                self._shutdown()
                raise StopIteration
            if self._recv_idx in self._reorder:
                err, batch = self._reorder.pop(self._recv_idx)
            else:
                # span = time the train loop starved on the workers;
                # `outstanding` is the dispatched-not-yet-received queue
                # depth the flight record needs to tell "workers slow"
                # from "queue sized wrong"
                from ..observability import trace as _trace
                with _trace.span("dataloader.get", batch_id=self._recv_idx,
                                 outstanding=self._outstanding,
                                 reordered=len(self._reorder)):
                    bid, err, batch = self.data_queue.get()
                if bid != self._recv_idx:
                    self._reorder[bid] = (err, batch)
                    continue
            self._recv_idx += 1
            self._outstanding -= 1
            self._dispatch()
            if isinstance(err, StopIteration):
                self._exhausted = True
                continue
            if err is not None:
                self._shutdown()
                raise err
            return _to_tensor_nest(batch, self.loader.return_list)

    def __iter__(self):
        return self

    def _shutdown(self):
        for q in self.index_queues:
            try:
                q.put(None)
            except Exception:  # noqa: BLE001
                pass
        # close the ring FIRST so writers blocked on a full ring wake with
        # BrokenPipeError and exit cleanly instead of being SIGTERM'd
        if isinstance(self.data_queue, _ShmDataQueue):
            self.data_queue.close()
        for w in self.workers:
            w.join(timeout=1)
            if w.is_alive():
                w.terminate()
        self.workers = []

    def __del__(self):
        if self.workers:
            self._shutdown()


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False,
                 shm_ring_capacity=64 << 20):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.batch_size = batch_size
        self.prefetch_factor = max(1, int(prefetch_factor))
        self.use_shared_memory = use_shared_memory
        self.shm_ring_capacity = shm_ring_capacity
        self.worker_init_fn = worker_init_fn
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif isinstance(dataset, IterableDataset):
            self.batch_sampler = None
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __iter__(self):
        if self.num_workers > 0:
            return _MultiProcessIter(self)
        return _SingleProcessIter(self)

    def __len__(self):
        if isinstance(self.dataset, IterableDataset):
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)

    def __call__(self):
        return iter(self)
