"""Distributed environment (reference: python/paddle/distributed/parallel.py
ParallelEnv — reads PADDLE_TRAINER_* env contract set by the launcher).
"""
from __future__ import annotations

import os


def get_rank() -> int:
    for key in ("PADDLE_TRAINER_ID", "PADDLE_RANK", "RANK"):
        if key in os.environ:
            return int(os.environ[key])
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def get_world_size() -> int:
    for key in ("PADDLE_TRAINERS_NUM", "PADDLE_WORLD_SIZE", "WORLD_SIZE"):
        if key in os.environ:
            return int(os.environ[key])
    try:
        import jax
        return jax.process_count()
    except Exception:
        return 1


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", get_rank()))

    @property
    def world_size(self):
        return get_world_size()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def device_id(self):
        return self.local_rank
