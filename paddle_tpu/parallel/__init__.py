"""paddle_tpu.parallel — the distributed stack (fleet/topology/collectives/
strategies).  Facade mirroring paddle.distributed; built on jax.sharding +
shard_map collectives instead of ProcessGroupNCCL (SURVEY §5.8)."""
from . import env  # noqa: F401
from .env import get_rank, get_world_size, ParallelEnv  # noqa: F401
