"""tpu-lint command line.

    python tools/tpu_lint.py paddle_tpu/ [--baseline tools/tpu_lint_baseline.json]
                                         [--format=text|json]
                                         [--tests tests/]
                                         [--checkers trace-hygiene,...]
                                         [--update-baseline [--force]]
                                         [--show-suppressed]

Exit codes: 0 clean (or all findings frozen in the baseline), 1 new
findings (or findings with no baseline given), 2 usage/baseline error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from . import baseline as baseline_mod
from .checkers import checker_by_name, default_checkers
from .core import Project, run


def _build_parser():
    p = argparse.ArgumentParser(
        prog="tpu_lint",
        description="AST-based TPU-hazard analyzer (trace hygiene, retrace "
                    "risk, thread/signal safety, fault-point coverage)")
    p.add_argument("paths", nargs="+",
                   help="package roots / files to analyze")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", metavar="FILE",
                   help="ratchet baseline JSON; only findings NOT frozen "
                        "there fail")
    p.add_argument("--tests", metavar="PATH", action="append", default=None,
                   help="tests root(s)/file(s) scanned as fault-point "
                        "coverage evidence; repeatable (default: ./tests "
                        "plus tools/chaos_smoke.py when present)")
    p.add_argument("--checkers", metavar="NAMES",
                   help="comma-separated subset (trace-hygiene, retrace, "
                        "concurrency, faults)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite --baseline with the current findings "
                        "(refuses to grow it)")
    p.add_argument("--force", action="store_true",
                   help="with --update-baseline: allow growth (initial "
                        "freeze / intentional re-baseline)")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also list findings silenced by '# tpu-lint: ok' "
                        "comments")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    try:
        checkers = (checker_by_name(
            [c.strip() for c in args.checkers.split(",") if c.strip()])
            if args.checkers else default_checkers())
    except ValueError as e:
        print(f"tpu-lint: {e}", file=sys.stderr)
        return 2

    project = Project()
    for path in args.paths:
        if not os.path.exists(path):
            print(f"tpu-lint: no such path: {path}", file=sys.stderr)
            return 2
        project.add_root(path)
    tests = args.tests if args.tests is not None else [
        t for t in ("tests", os.path.join("tools", "chaos_smoke.py"))
        if os.path.exists(t)]
    for t in tests:
        project.add_tests_root(t)

    findings, suppressed = run(project, checkers)

    if args.update_baseline:
        if not args.baseline:
            print("tpu-lint: --update-baseline needs --baseline FILE",
                  file=sys.stderr)
            return 2
        try:
            baseline_mod.update(args.baseline, findings, force=args.force)
        except ValueError as e:
            print(f"tpu-lint: {e}", file=sys.stderr)
            return 2
        print(f"tpu-lint: baseline written to {args.baseline} "
              f"({len(findings)} finding(s))")
        return 0

    new, fixed = findings, []
    if args.baseline:
        try:
            data = baseline_mod.load(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"tpu-lint: cannot read baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        new, fixed = baseline_mod.compare(findings, data)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "new": [f.to_dict() for f in new],
            "fixed_fingerprints": fixed,
            "suppressed": [f.to_dict() for f in suppressed],
            "counts": {"findings": len(findings), "new": len(new),
                       "fixed": len(fixed), "suppressed": len(suppressed)},
        }, indent=1))
    else:
        shown = new if args.baseline else findings
        for f in shown:
            print(f.render())
        if args.show_suppressed:
            for f in suppressed:
                print(f"suppressed: {f.render()}")
        frozen = len(findings) - len(new)
        summary = (f"tpu-lint: {len(findings)} finding(s)"
                   f" ({len(suppressed)} suppressed in-code)")
        if args.baseline:
            summary += (f"; baseline: {frozen} frozen, {len(new)} NEW, "
                        f"{len(fixed)} fixed")
            if fixed:
                summary += ("  — baseline can shrink: re-run with "
                            "--update-baseline")
        print(summary, file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
