"""tpu-lint — AST-based static analysis for the TPU hazard classes this
repo has paid to learn at runtime (ISSUE 7).

Four checkers over a shared resolution layer (imports, decorators,
scopes, a best-effort call graph):

* **trace-hygiene** — host syncs and python control flow inside
  jit-reachable code (the recompile/roundtrip killers the retrace
  sentinel and DeviceLossList catch only after the fact);
* **retrace** — signature hazards at ``jax.jit``/``shard_map`` entry
  points (jit-in-loop, mutable defaults, unhashable statics,
  data-dependent shapes);
* **concurrency** — class attributes shared between a
  ``threading.Thread`` target and its callers without a lock, and
  non-async-signal-safe work in ``signal.signal`` handlers;
* **faults** — every declared ``fault_point`` seam must appear in the
  crash-matrix tests and in ``faults.CATALOGUE``.

Violations are structured :class:`Finding`s gated by a ratchet baseline
(``tools/tpu_lint_baseline.json``): pre-existing findings are frozen,
new ones fail CI, the baseline may only shrink.  Suppress a justified
finding in place with ``# tpu-lint: ok(rule)``.

This package is stdlib-only (no jax, no paddle_tpu imports) so the CLI
(``tools/tpu_lint.py``) can run it anywhere, fast.
"""
from __future__ import annotations

from . import baseline
from .checkers import checker_by_name, default_checkers
from .core import Checker, Finding, Project, run
from .module import FuncInfo, ModuleInfo

__all__ = ["Finding", "Checker", "Project", "run", "ModuleInfo",
           "FuncInfo", "baseline", "default_checkers", "checker_by_name",
           "analyze"]


def analyze(roots, tests_root=None, checkers=None):
    """One-call API: parse `roots`, run the checkers, return
    (findings, suppressed, project)."""
    project = Project()
    for root in ([roots] if isinstance(roots, str) else roots):
        project.add_root(root)
    if tests_root:
        project.add_tests_root(tests_root)
    findings, suppressed = run(project,
                               default_checkers() if checkers is None
                               else checkers)
    return findings, suppressed, project
