"""tpu-lint core — findings, the checker plugin base, and the project
(file set) the checkers run over.

A :class:`Finding` is one structured violation: rule id, file:line, the
enclosing symbol, a message, and a fix hint.  Its :meth:`fingerprint`
deliberately excludes the line number so the ratchet baseline survives
unrelated edits above a frozen finding.

A :class:`Checker` sees every module (``check_module``) and then the
whole project (``finalize``) — per-file rules live in the former,
cross-file rules (jit reachability, fault-point coverage) in the latter.
"""
from __future__ import annotations

import ast
import os

from .module import ModuleInfo


class Finding:
    __slots__ = ("rule", "path", "line", "col", "symbol", "message", "hint")

    def __init__(self, rule: str, path: str, line: int, col: int = 0,
                 symbol: str = "", message: str = "", hint: str = ""):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.symbol = symbol
        self.message = message
        self.hint = hint

    def fingerprint(self) -> str:
        # line-free on purpose: edits elsewhere in the file must not
        # invalidate baseline entries
        return f"{self.rule}::{self.path}::{self.symbol}::{self.message}"

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule, self.message)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "symbol": self.symbol,
                "message": self.message, "hint": self.hint}

    def render(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        out = f"{loc}: [{self.rule}] {self.message}"
        if self.symbol:
            out += f"  (in {self.symbol})"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def __repr__(self):
        return f"Finding({self.rule}, {self.path}:{self.line})"


class Checker:
    """Plugin base.  Subclasses set ``name`` + ``rules`` and implement
    either hook; both receive already-parsed :class:`ModuleInfo`s."""

    name: str = ""
    rules: tuple = ()

    def check_module(self, mod: ModuleInfo, project: "Project"):
        return ()

    def finalize(self, project: "Project"):
        return ()


class Project:
    """The analyzed file set: scan roots (package code) plus an optional
    tests root (coverage evidence for the fault-point rule — test files
    are scanned for string literals, not linted)."""

    def __init__(self):
        self.modules: list[ModuleInfo] = []
        self.by_dotted: dict[str, ModuleInfo] = {}
        self.parse_errors: list[Finding] = []
        self.test_files: list[tuple[str, str]] = []  # (rel, source)
        self._callgraph = None

    # -- loading -------------------------------------------------------------
    @staticmethod
    def _rel(path: str) -> str:
        rel = os.path.relpath(path)
        if rel.startswith(".."):
            rel = path
        return rel.replace(os.sep, "/")

    @staticmethod
    def _dotted_for(path: str) -> str:
        """Dotted module name from the path by walking up through package
        dirs (dirs holding __init__.py)."""
        path = os.path.abspath(path)
        parts = [os.path.splitext(os.path.basename(path))[0]]
        d = os.path.dirname(path)
        while os.path.isfile(os.path.join(d, "__init__.py")):
            parts.append(os.path.basename(d))
            d = os.path.dirname(d)
        if parts[0] == "__init__":
            parts = parts[1:] or [""]
        return ".".join(reversed(parts))

    def add_file(self, path: str):
        rel = self._rel(path)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            mod = ModuleInfo(path, rel, source, self._dotted_for(path))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            line = getattr(e, "lineno", 0) or 0
            self.parse_errors.append(Finding(
                "analysis.parse-error", rel, line,
                message=f"could not parse: {type(e).__name__}: {e}"))
            return
        self.modules.append(mod)
        if mod.dotted:
            self.by_dotted[mod.dotted] = mod

    def add_root(self, root: str):
        if os.path.isfile(root):
            self.add_file(root)
            return
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__" and
                                 not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    self.add_file(os.path.join(dirpath, fn))

    def add_tests_root(self, root: str):
        if not root:
            return
        if os.path.isfile(root):
            self.add_test_file(root)
            return
        if not os.path.isdir(root):
            return
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__" and
                                 not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    p = os.path.join(dirpath, fn)
                    try:
                        with open(p, encoding="utf-8") as f:
                            self.test_files.append((self._rel(p), f.read()))
                    except (OSError, UnicodeDecodeError):
                        continue

    def add_test_file(self, path: str):
        try:
            with open(path, encoding="utf-8") as f:
                self.test_files.append((self._rel(path), f.read()))
        except (OSError, UnicodeDecodeError):
            pass

    # -- shared analyses -----------------------------------------------------
    def callgraph(self):
        """Jit entry points + reachability, built once and shared by the
        trace-hygiene and retrace checkers."""
        if self._callgraph is None:
            from .callgraph import CallGraph
            self._callgraph = CallGraph(self)
        return self._callgraph

    def test_string_literals(self) -> set[str]:
        """Every string literal in the tests root (plus the contents of
        PADDLE_TPU_FAULTS-style colon specs) — the coverage evidence the
        fault-point rule checks seams against."""
        out: set[str] = set()
        for _rel, source in self.test_files:
            try:
                tree = ast.parse(source)
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str):
                    out.add(node.value)
                    # "train.step:kill:after=5,fs.upload:raise" env specs
                    for part in node.value.split(","):
                        out.add(part.split(":")[0].strip())
        return out

    def module_by_rel_suffix(self, suffix: str) -> ModuleInfo | None:
        for mod in self.modules:
            if mod.rel.endswith(suffix):
                return mod
        return None


def run(project: Project, checkers) -> tuple[list[Finding], list[Finding]]:
    """Run checkers over the project; returns (findings, suppressed) both
    sorted.  Suppression comments are applied here so checkers never need
    to know about them."""
    raw: list[Finding] = list(project.parse_errors)
    for checker in checkers:
        for mod in project.modules:
            raw.extend(checker.check_module(mod, project))
        raw.extend(checker.finalize(project))
    by_rel = {m.rel: m for m in project.modules}
    findings, suppressed = [], []
    for f in raw:
        mod = by_rel.get(f.path)
        if mod is not None and mod.is_suppressed(f.rule, f.line):
            suppressed.append(f)
        else:
            findings.append(f)
    findings.sort(key=Finding.sort_key)
    suppressed.sort(key=Finding.sort_key)
    return findings, suppressed
