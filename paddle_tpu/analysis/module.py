"""Per-file AST model for tpu-lint — parse once, resolve names once.

A :class:`ModuleInfo` wraps one parsed source file with the three
resolutions every checker needs and none wants to re-implement:

* **imports** — local alias -> dotted origin (``jnp`` -> ``jax.numpy``,
  ``faults`` -> ``paddle_tpu.testing.faults``), with relative imports
  resolved against the module's own dotted name;
* **functions** — every ``def`` (module-level, method, nested) as a
  :class:`FuncInfo` with qualname, enclosing class, and lexical parent,
  so call targets can be looked up through the scope chain;
* **suppressions** — ``# tpu-lint: ok(rule)`` comments by line.

Everything here is stdlib-only on purpose: the CLI runs the analyzer
without importing paddle_tpu (or jax) at all.
"""
from __future__ import annotations

import ast
import re

_SUPPRESS_RE = re.compile(r"#\s*tpu-lint:\s*ok(?:\(([^)]*)\))?")


class FuncInfo:
    """One function/method/nested def with its lexical context."""

    __slots__ = ("node", "module", "qualname", "cls", "parent", "local_defs")

    def __init__(self, node, module, qualname, cls=None, parent=None):
        self.node = node
        self.module = module
        self.qualname = qualname
        self.cls = cls                  # enclosing ClassDef or None
        self.parent = parent            # enclosing FuncInfo or None
        self.local_defs: dict[str, "FuncInfo"] = {}

    @property
    def name(self):
        return self.node.name

    def params(self) -> list[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    def __repr__(self):
        return f"FuncInfo({self.module.rel}::{self.qualname})"


def body_nodes(func_node):
    """Walk a function body, NOT descending into nested def/class bodies
    (those are separate FuncInfos / scopes)."""
    stack = list(func_node.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # decorators/defaults evaluate in the enclosing scope
            stack.extend(getattr(node, "decorator_list", ()))
            continue
        stack.extend(ast.iter_child_nodes(node))


class ModuleInfo:
    def __init__(self, path: str, rel: str, source: str,
                 dotted: str = ""):
        self.path = path
        self.rel = rel                  # display/baseline path (fwd slashes)
        self.source = source
        self.dotted = dotted            # e.g. "paddle_tpu.nn.clip"
        self.tree = ast.parse(source, filename=path)
        self.imports: dict[str, str] = {}
        self.functions: list[FuncInfo] = []
        self.func_of_node: dict[ast.AST, FuncInfo] = {}
        self.top_defs: dict[str, FuncInfo] = {}
        self.classes: list[ast.ClassDef] = []
        self.methods: dict[str, dict[str, FuncInfo]] = {}  # cls -> name -> fi
        self.suppressions: dict[int, set[str] | None] = {}  # None == all rules
        self._set_parents()
        self._collect_imports()
        self._collect_functions()
        self._collect_suppressions()

    # -- construction --------------------------------------------------------
    def _set_parents(self):
        self.tree.parent = None
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child.parent = node

    def _collect_imports(self):
        pkg = self.dotted.rsplit(".", 1)[0] if "." in self.dotted else ""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    self.imports[local] = (alias.name if alias.asname
                                           else alias.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    # relative: drop (level-1) trailing components of the
                    # module's package, then append the stated module
                    parts = pkg.split(".") if pkg else []
                    if node.level - 1 <= len(parts):
                        parts = parts[:len(parts) - (node.level - 1)]
                        base = ".".join(parts + ([node.module]
                                                 if node.module else []))
                    else:
                        base = node.module or ""
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    self.imports[local] = (f"{base}.{alias.name}" if base
                                           else alias.name)

    def _collect_functions(self):
        def visit(node, cls, parent, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qn = f"{prefix}{child.name}"
                    fi = FuncInfo(child, self, qn, cls=cls, parent=parent)
                    self.functions.append(fi)
                    self.func_of_node[child] = fi
                    if parent is not None:
                        parent.local_defs[child.name] = fi
                    elif cls is None:
                        self.top_defs[child.name] = fi
                    else:
                        self.methods.setdefault(cls.name, {})[child.name] = fi
                    visit(child, cls, fi, qn + ".")
                elif isinstance(child, ast.ClassDef):
                    self.classes.append(child)
                    self.methods.setdefault(child.name, {})
                    visit(child, child, None, f"{prefix}{child.name}.")
                else:
                    visit(child, cls, parent, prefix)
        visit(self.tree, None, None, "")

    def _collect_suppressions(self):
        for i, line in enumerate(self.source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = m.group(1)
            if rules is None or not rules.strip():
                self.suppressions[i] = None
            else:
                self.suppressions[i] = {r.strip() for r in rules.split(",")
                                        if r.strip()}

    # -- queries -------------------------------------------------------------
    def enclosing_function(self, node) -> FuncInfo | None:
        cur = getattr(node, "parent", None)
        while cur is not None:
            fi = self.func_of_node.get(cur)
            if fi is not None:
                return fi
            cur = getattr(cur, "parent", None)
        return None

    def dotted_name(self, node) -> str | None:
        """Resolve a Name/Attribute chain to a dotted path through the
        import map (``jnp.zeros`` -> ``jax.numpy.zeros``).  Returns None
        for anything that is not a plain chain (calls, subscripts...)."""
        parts = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        base = self.imports.get(cur.id, cur.id)
        parts.append(base)
        return ".".join(reversed(parts))

    def suppressed_rules(self, line: int):
        """Union of suppression specs on `line` and the line above;
        returns (found, rules-or-None)."""
        found, rules = False, set()
        for ln in (line, line - 1):
            if ln in self.suppressions:
                found = True
                spec = self.suppressions[ln]
                if spec is None:
                    return True, None
                rules |= spec
        return found, (rules if found else None)

    def is_suppressed(self, rule: str, line: int) -> bool:
        found, rules = self.suppressed_rules(line)
        if not found:
            return False
        if rules is None:
            return True
        for r in rules:
            if rule == r or rule.startswith(r + "."):
                return True
        return False
