"""concurrency — unguarded shared attributes and signal-handler safety.

``concurrency.unguarded-shared-attr``: within a class that runs a
``threading.Thread`` over one of its own methods, an attribute that is
*written* on one side (the thread-target call closure vs. every other
method) and *accessed* on the other, where at least one of those
accesses is not under a ``with self._lock:``-style guard.  The repo's
``*_locked`` method-name convention (callers hold the lock) is honored,
and attributes that are themselves synchronization objects
(Lock/Event/Queue...) are exempt — their methods are atomic.

``concurrency.signal-unsafe``: a handler registered via
``signal.signal`` (or anything it calls in the same module) performing
work that is not async-signal-safe — acquiring locks, logging, file IO,
allocation-heavy formatting.  A signal can interrupt the holder of the
very lock the handler then takes: instant deadlock on the shutdown
path, the hardest hang to reproduce.

Known limits (by design, documented in docs/static_analysis.md): thread
relationships across classes are resolved by method *name* within one
module only; container mutation through method calls
(``self._pool.alloc()``) is not tracked — only attribute stores,
augmented assigns, and subscript stores on ``self.<attr>``.
"""
from __future__ import annotations

import ast

from ..core import Checker, Finding
from ..module import FuncInfo, ModuleInfo, body_nodes

R_SHARED = "concurrency.unguarded-shared-attr"
R_SIGNAL = "concurrency.signal-unsafe"

_LOCK_TYPES = {"Lock", "RLock", "Condition"}
_EXEMPT_TYPES = {"Lock", "RLock", "Condition", "Event", "Semaphore",
                 "BoundedSemaphore", "Barrier", "Queue", "LifoQueue",
                 "PriorityQueue", "SimpleQueue", "local"}
_SKIP_METHODS = {"__init__", "__del__", "__repr__", "__str__"}
_HINT_SHARED = ("guard both sides with the class lock (`with self._lock:`)"
                ", move the access into a `*_locked` helper called under "
                "the lock, or suppress with a rationale if the race is "
                "benign (e.g. a monotonic monitor flag)")
_HINT_SIGNAL = ("keep handlers to setting a flag/Event and re-raising; do "
                "the real work at the next safe point (step boundary), "
                "like framework/preemption.py's request flag")

# call patterns that are not async-signal-safe
_UNSAFE_FINAL = {"acquire": "acquires a lock",
                 "warning": "logs", "info": "logs", "error": "logs",
                 "debug": "logs", "critical": "logs",
                 "makedirs": "touches the filesystem",
                 "dump": "formats/allocates", "dumps": "formats/allocates",
                 "strftime": "allocates"}
_UNSAFE_BARE = {"print": "writes stdout", "open": "opens a file"}


class _Access:
    __slots__ = ("attr", "write", "guarded", "method", "line", "col")

    def __init__(self, attr, write, guarded, method, line, col):
        self.attr = attr
        self.write = write
        self.guarded = guarded
        self.method = method
        self.line = line
        self.col = col


def _sync_typed_attrs(mod: ModuleInfo, cls: ast.ClassDef
                      ) -> tuple[set[str], set[str]]:
    """(lock-ish attrs, exempt sync-object attrs) from __init__ assigns
    like ``self._lock = threading.Lock()``."""
    locks, exempt = set(), set()
    init = mod.methods.get(cls.name, {}).get("__init__")
    if init is None:
        return locks, exempt
    for node in body_nodes(init.node):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        d = mod.dotted_name(node.value.func)
        final = d.rsplit(".", 1)[-1] if d else None
        for t in node.targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                if final in _LOCK_TYPES:
                    locks.add(t.attr)
                    exempt.add(t.attr)
                elif final in _EXEMPT_TYPES:
                    exempt.add(t.attr)
    return locks, exempt


def _guard_ancestor(node, lock_attrs: set[str]) -> bool:
    """Lexically inside `with self.<lock>:` (or a with over anything whose
    name smells like a lock)?"""
    cur = getattr(node, "parent", None)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
        if isinstance(cur, ast.With):
            for item in cur.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Attribute) and \
                        isinstance(ctx.value, ast.Name) and \
                        ctx.value.id == "self":
                    name = ctx.attr.lower()
                    if ctx.attr in lock_attrs or "lock" in name or \
                            name.endswith(("_cv", "_cond")):
                        return True
        cur = getattr(cur, "parent", None)
    return False


def _self_attr_accesses(mod: ModuleInfo, fi: FuncInfo,
                        lock_attrs: set[str]) -> list[_Access]:
    out = []
    locked_method = fi.name.endswith("_locked")
    for node in body_nodes(fi.node):
        if not isinstance(node, ast.Attribute) or \
                not isinstance(node.value, ast.Name) or \
                node.value.id != "self":
            continue
        parent = getattr(node, "parent", None)
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        # self.x[i] = v / self.x[i] += v / del self.x[i]: container write
        if not write and isinstance(parent, ast.Subscript) and \
                parent.value is node and \
                isinstance(parent.ctx, (ast.Store, ast.Del)):
            write = True
        # method calls (self.x.append(...)) count as reads of x only
        guarded = locked_method or _guard_ancestor(node, lock_attrs)
        out.append(_Access(node.attr, write, guarded, fi.name,
                           node.lineno, node.col_offset))
    return out


class ConcurrencyChecker(Checker):
    name = "concurrency"
    rules = (R_SHARED, R_SIGNAL)

    def check_module(self, mod: ModuleInfo, project):
        out = list(self._shared_attrs(mod))
        out.extend(self._signal_handlers(mod))
        return out

    # -- shared attributes ---------------------------------------------------
    def _thread_targets(self, mod: ModuleInfo) -> list[tuple[str, str]]:
        """(class, method) pairs passed as Thread(target=self.m)."""
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = mod.dotted_name(node.func)
            if not d or d.rsplit(".", 1)[-1] != "Thread":
                continue
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
            if target is None and node.args:
                target = node.args[0]
            if isinstance(target, ast.Attribute) and \
                    isinstance(target.value, ast.Name) and \
                    target.value.id == "self":
                fi = mod.enclosing_function(node)
                if fi is not None and fi.cls is not None:
                    out.append((fi.cls.name, target.attr))
        return out

    def _thread_closure(self, mod: ModuleInfo,
                        roots: list[tuple[str, str]]) -> set[tuple[str, str]]:
        """BFS from thread targets over self.m() calls (same class) and
        name-matched <expr>.m() calls into other classes of the module."""
        method_owners: dict[str, list[str]] = {}
        for cls_name, meths in mod.methods.items():
            for m in meths:
                method_owners.setdefault(m, []).append(cls_name)
        seen = set()
        work = [r for r in roots if r[1] in mod.methods.get(r[0], {})]
        while work:
            key = work.pop()
            if key in seen:
                continue
            seen.add(key)
            cls_name, meth = key
            fi = mod.methods[cls_name][meth]
            for node in body_nodes(fi.node):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Attribute):
                    continue
                callee = node.func.attr
                base = node.func.value
                if isinstance(base, ast.Name) and base.id == "self":
                    if callee in mod.methods.get(cls_name, {}):
                        work.append((cls_name, callee))
                    continue
                # cross-class, name-based: self._owner._note_depth(...)
                owners = method_owners.get(callee, ())
                if len(owners) == 1 and owners[0] != cls_name:
                    work.append((owners[0], callee))
        return seen

    def _shared_attrs(self, mod: ModuleInfo):
        roots = self._thread_targets(mod)
        if not roots:
            return
        closure = self._thread_closure(mod, roots)
        touched_classes = {c for c, _ in closure}
        for cls in mod.classes:
            if cls.name not in touched_classes:
                continue
            locks, exempt = _sync_typed_attrs(mod, cls)
            thread_acc: dict[str, list[_Access]] = {}
            main_acc: dict[str, list[_Access]] = {}
            for meth, fi in mod.methods.get(cls.name, {}).items():
                if meth in _SKIP_METHODS:
                    continue
                side = thread_acc if (cls.name, meth) in closure else main_acc
                for a in _self_attr_accesses(mod, fi, locks):
                    if a.attr in exempt:
                        continue
                    side.setdefault(a.attr, []).append(a)
            for attr in sorted(set(thread_acc) & set(main_acc)):
                t, m = thread_acc[attr], main_acc[attr]
                t_writes = [a for a in t if a.write]
                m_writes = [a for a in m if a.write]
                # race pair: a write on one side vs any access on the
                # other, with at least one of the two unguarded; anchor
                # at the unguarded write when there is one
                def _pick(writes, others):
                    if not writes or not others:
                        return None
                    uw = [a for a in writes if not a.guarded]
                    if uw:
                        return uw[0]
                    uo = [a for a in others if not a.guarded]
                    return uo[0] if uo else None

                anchor = _pick(t_writes, m) or _pick(m_writes, t)
                if anchor is None:
                    continue
                t_meths = sorted({a.method for a in t})
                m_meths = sorted({a.method for a in m})
                yield Finding(
                    R_SHARED, mod.rel, anchor.line, anchor.col,
                    symbol=f"{cls.name}.{anchor.method}",
                    message=(f"attribute `self.{attr}` of `{cls.name}` is "
                             f"shared between the thread side "
                             f"({', '.join(t_meths)}) and callers "
                             f"({', '.join(m_meths)}) with unguarded "
                             f"{'write' if anchor.write else 'access'} in "
                             f"`{anchor.method}`"),
                    hint=_HINT_SHARED)

    # -- signal handlers -----------------------------------------------------
    def _module_locks(self, mod: ModuleInfo) -> set[str]:
        out = set()
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                d = mod.dotted_name(node.value.func)
                if d and d.rsplit(".", 1)[-1] in _LOCK_TYPES:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            out.add(t.id)
        return out

    def _resolve_handler(self, mod: ModuleInfo, expr) -> FuncInfo | None:
        if isinstance(expr, ast.Name):
            fi = mod.top_defs.get(expr.id)
            if fi is not None:
                return fi
            scope = mod.enclosing_function(expr)
            while scope is not None:
                if expr.id in scope.local_defs:
                    return scope.local_defs[expr.id]
                scope = scope.parent
            return None
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name):
            # factory: signal.signal(sig, _make_handler(sig)) — follow the
            # returned nested def
            factory = mod.top_defs.get(expr.func.id)
            if factory is not None:
                for node in body_nodes(factory.node):
                    if isinstance(node, ast.Return) and \
                            isinstance(node.value, ast.Name) and \
                            node.value.id in factory.local_defs:
                        return factory.local_defs[node.value.id]
        return None

    def _signal_handlers(self, mod: ModuleInfo):
        handlers: list[FuncInfo] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = mod.dotted_name(node.func)
            if not d or not (d == "signal.signal" or
                             d.endswith(".signal.signal")):
                continue
            if len(node.args) < 2:
                continue
            h = self._resolve_handler(mod, node.args[1])
            if h is not None and h not in handlers:
                handlers.append(h)
        if not handlers:
            return
        locks = self._module_locks(mod)
        for h in handlers:
            # handler + everything it calls in this module
            closure, work = [], [h]
            while work:
                fi = work.pop()
                if fi in closure:
                    continue
                closure.append(fi)
                for node in body_nodes(fi.node):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Name):
                        t = mod.top_defs.get(node.func.id)
                        if t is not None:
                            work.append(t)
            for fi in closure:
                yield from self._unsafe_calls(mod, fi, h, locks)

    def _unsafe_calls(self, mod: ModuleInfo, fi: FuncInfo, handler: FuncInfo,
                      locks: set[str]):
        where = ("" if fi is handler else
                 f" (reached from handler `{handler.qualname}`)")
        for node in body_nodes(fi.node):
            what = None
            if isinstance(node, ast.With):
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Name) and (
                            ctx.id in locks or "lock" in ctx.id.lower()):
                        what = f"`with {ctx.id}:` acquires a lock"
            elif isinstance(node, ast.Call):
                f = node.func
                d = mod.dotted_name(f)
                final = d.rsplit(".", 1)[-1] if d else None
                if isinstance(f, ast.Name) and f.id in _UNSAFE_BARE:
                    what = f"`{f.id}()` {_UNSAFE_BARE[f.id]}"
                elif d and d.endswith("flight.record"):
                    what = "`flight.record()` allocates and locks the ring"
                elif final in _UNSAFE_FINAL and isinstance(f, ast.Attribute):
                    base = f.value
                    base_name = (base.id if isinstance(base, ast.Name)
                                 else None)
                    if final == "acquire" or (base_name and (
                            base_name in ("logger", "logging", "log",
                                          "json", "os", "time"))):
                        what = f"`{d}()` {_UNSAFE_FINAL[final]}"
            if what is not None:
                yield Finding(
                    R_SIGNAL, mod.rel, node.lineno, node.col_offset,
                    symbol=fi.qualname,
                    message=(f"non-async-signal-safe work in signal "
                             f"handler path: {what} in `{fi.qualname}`"
                             f"{where}"),
                    hint=_HINT_SIGNAL)
