"""retrace-hazard — the static complement of observability/retrace.py.

The runtime sentinel counts recompiles after they happen; these rules
catch the signature mistakes that cause them before a TPU ever spins:

* ``retrace.jit-in-loop`` — ``jax.jit``/``shard_map`` called inside a
  ``for``/``while`` body: a fresh wrapper per iteration has an empty
  cache, so every call traces + compiles again (the retrace sentinel's
  storm case, guaranteed).
* ``retrace.mutable-default`` — a jit entry with a list/dict/set
  default: unhashable under the jit cache key when passed static, and a
  shared mutable across traces otherwise.
* ``retrace.unhashable-static`` — ``static_argnums``/``static_argnames``
  pointing at a parameter whose default is unhashable: every call raises
  or re-keys the cache.
* ``retrace.traced-dim-shape`` — a traced parameter used directly as a
  dimension in ``jnp.zeros/ones/full/empty/arange/reshape`` inside a jit
  entry: the shape becomes data-dependent, so every distinct value is a
  new signature (per-call recompile).  ``x.shape[i]`` is fine — that is
  static under trace.
"""
from __future__ import annotations

import ast

from ..callgraph import is_jit_wrapper
from ..core import Checker, Finding
from ..module import ModuleInfo

R_LOOP = "retrace.jit-in-loop"
R_MUT = "retrace.mutable-default"
R_STATIC = "retrace.unhashable-static"
R_DIM = "retrace.traced-dim-shape"

_SHAPE_FNS = {"zeros", "ones", "full", "empty", "arange", "reshape",
              "broadcast_to", "tile"}
_MUTABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
            ast.SetComp)


def _in_loop(node) -> bool:
    cur = getattr(node, "parent", None)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
        if isinstance(cur, (ast.For, ast.While)):
            return True
        cur = getattr(cur, "parent", None)
    return False


def _defaults_by_param(node: ast.FunctionDef) -> dict[str, ast.AST]:
    args = node.args
    pos = args.posonlyargs + args.args
    out = {}
    for p, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
        out[p.arg] = d
    for p, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None:
            out[p.arg] = d
    return out


class RetraceChecker(Checker):
    name = "retrace"
    rules = (R_LOOP, R_MUT, R_STATIC, R_DIM)

    def check_module(self, mod: ModuleInfo, project):
        out = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    is_jit_wrapper(mod.dotted_name(node.func)) and \
                    _in_loop(node):
                fi = mod.enclosing_function(node)
                out.append(Finding(
                    R_LOOP, mod.rel, node.lineno, node.col_offset,
                    symbol=fi.qualname if fi else "<module>",
                    message=("jit/shard_map wrapper created inside a loop "
                             "body — a fresh wrapper retraces and "
                             "recompiles on every iteration"),
                    hint=("hoist the jitted callable out of the loop (or "
                          "cache it once, like spmd.py's _unflatten_jit)")))
        return out

    def finalize(self, project):
        cg = project.callgraph()
        out = []
        for entry in cg.entries:
            fi = entry.func
            mod = fi.module
            defaults = _defaults_by_param(fi.node)
            for pname, d in defaults.items():
                if isinstance(d, _MUTABLE):
                    rule, why = (R_STATIC, "declared static") \
                        if pname in entry.static_params else \
                        (R_MUT, "a mutable default")
                    out.append(Finding(
                        rule, mod.rel, d.lineno, d.col_offset,
                        symbol=fi.qualname,
                        message=(f"jit entry `{fi.qualname}` parameter "
                                 f"`{pname}` has {why} "
                                 f"{type(d).__name__.lower()} — unhashable "
                                 "under the jit cache key"),
                        hint=("use a tuple / frozen value, or pass it "
                              "dynamically instead of static")))
            out.extend(self._traced_dims(entry))
        return out

    def _traced_dims(self, entry):
        fi = entry.func
        mod = fi.module
        traced = set(entry.traced_params())
        out = []
        from ..module import body_nodes
        for node in body_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            final = None
            if isinstance(f, ast.Attribute):
                final = f.attr
            elif isinstance(f, ast.Name):
                final = f.id
            if final not in _SHAPE_FNS:
                continue
            d = mod.dotted_name(f)
            # only numpy-like constructors (jnp.zeros, np.zeros, bare
            # from-import) and .reshape methods
            if d and not (d.startswith("jax.numpy.") or
                          d.startswith("numpy.") or "." not in d):
                if final not in ("reshape", "broadcast_to", "tile"):
                    continue
            shape_args = list(node.args[:1]) + [
                kw.value for kw in node.keywords if kw.arg == "shape"]
            if final in ("reshape", "arange", "tile"):
                shape_args = list(node.args) + shape_args
            for arg in shape_args:
                name = self._bare_traced_name(arg, traced)
                if name:
                    out.append(Finding(
                        R_DIM, mod.rel, node.lineno, node.col_offset,
                        symbol=fi.qualname,
                        message=(f"traced parameter `{name}` used as a "
                                 f"dimension in `{final}` inside jit entry "
                                 f"`{fi.qualname}` — data-dependent shape, "
                                 "recompiles per distinct value"),
                        hint=("derive the size from a static `.shape` or "
                              "pass it via static_argnums")))
                    break
        return out

    @staticmethod
    def _bare_traced_name(arg, traced: set[str]) -> str | None:
        """A traced param appearing as a bare dimension (`n` or inside a
        tuple/arithmetic), NOT through `.shape[i]` (static under trace)."""
        skip: set[int] = set()
        for node in ast.walk(arg):
            if isinstance(node, ast.Attribute):
                for sub in ast.walk(node):
                    skip.add(id(sub))
        for node in ast.walk(arg):
            if isinstance(node, ast.Name) and node.id in traced and \
                    id(node) not in skip:
                return node.id
        return None
