"""tpu-lint checker registry.  ``default_checkers()`` returns fresh
instances (checkers may carry per-run state, e.g. the fault-point
declaration index)."""
from __future__ import annotations

from .concurrency import ConcurrencyChecker
from .faultpoints import FaultPointChecker
from .retrace import RetraceChecker
from .trace_hygiene import TraceHygieneChecker

__all__ = ["default_checkers", "checker_by_name", "TraceHygieneChecker",
           "RetraceChecker", "ConcurrencyChecker", "FaultPointChecker"]

_REGISTRY = (TraceHygieneChecker, RetraceChecker, ConcurrencyChecker,
             FaultPointChecker)


def default_checkers():
    return [cls() for cls in _REGISTRY]


def checker_by_name(names):
    sel = []
    known = {cls().name: cls for cls in _REGISTRY}
    for n in names:
        if n not in known:
            raise ValueError(
                f"unknown checker {n!r}; known: {sorted(known)}")
        sel.append(known[n]())
    return sel
