"""fault-point coverage — every declared crash seam must be exercised.

The fault-injection harness (``paddle_tpu/testing/faults.py``) only
pays off if every ``fault_point("name")`` seam in production code is
actually crashed in the test matrix; an uncovered seam is a crash path
that ships untested.  Two rules:

* ``faults.uncovered-seam`` — a seam declared in the package (literal
  ``fault_point("...")`` call or an entry of ``faults.CATALOGUE``) that
  never appears as a string literal anywhere under the tests root
  (``faults.inject(...)``, ``faults.arm(...)``, parametrize lists, and
  ``PADDLE_TPU_FAULTS`` env specs all count).
* ``faults.uncatalogued-seam`` — a literal seam not listed in
  ``CATALOGUE`` in faults.py: the catalogue is the operator-facing index
  (docs/robustness.md), so a seam missing from it is invisible to chaos
  tooling.

Dynamic seam names (``fault_point(name)``) are ignored — the catalogue
is how those stay accounted for.
"""
from __future__ import annotations

import ast

from ..core import Checker, Finding

R_UNCOVERED = "faults.uncovered-seam"
R_UNCATALOGUED = "faults.uncatalogued-seam"
_HINT_COVER = ("add a crash-matrix case (tests/test_robustness.py style: "
               "`with faults.inject(<seam>): ...` asserting the "
               "post-crash state) or a PADDLE_TPU_FAULTS chaos lane")
_HINT_CATALOGUE = ("add the seam to CATALOGUE in "
                   "paddle_tpu/testing/faults.py and the docs/"
                   "robustness.md catalogue")


class FaultPointChecker(Checker):
    name = "faults"
    rules = (R_UNCOVERED, R_UNCATALOGUED)

    def __init__(self):
        # seam -> first declaration site (mod.rel, line)
        self._declared: dict[str, tuple[str, int]] = {}
        self._catalogue: dict[str, tuple[str, int]] = {}

    def check_module(self, mod, project):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = mod.dotted_name(node.func)
            if not d or d.rsplit(".", 1)[-1] != "fault_point":
                continue
            if not node.args or not (
                    isinstance(node.args[0], ast.Constant) and
                    isinstance(node.args[0].value, str)):
                continue  # dynamic name: covered via the catalogue
            name = node.args[0].value
            self._declared.setdefault(name, (mod.rel, node.lineno))
        if mod.rel.endswith("testing/faults.py"):
            for node in mod.tree.body:
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "CATALOGUE"
                        for t in node.targets):
                    for c in ast.walk(node.value):
                        if isinstance(c, ast.Constant) and \
                                isinstance(c.value, str):
                            self._catalogue.setdefault(
                                c.value, (mod.rel, c.lineno))
        return ()

    def finalize(self, project):
        out = []
        covered = project.test_string_literals()
        all_seams = dict(self._catalogue)
        all_seams.update(self._declared)
        for name in sorted(all_seams):
            rel, line = all_seams[name]
            if name not in covered:
                out.append(Finding(
                    R_UNCOVERED, rel, line, symbol=name,
                    message=(f"fault point `{name}` is declared but never "
                             "exercised by the crash-matrix tests"),
                    hint=_HINT_COVER))
        if self._catalogue:
            for name in sorted(self._declared):
                if name not in self._catalogue:
                    rel, line = self._declared[name]
                    out.append(Finding(
                        R_UNCATALOGUED, rel, line, symbol=name,
                        message=(f"fault point `{name}` is missing from "
                                 "faults.CATALOGUE"),
                        hint=_HINT_CATALOGUE))
        return out
