"""trace-hygiene — host syncs and python control flow where jax traces.

Three rules:

* ``trace-hygiene.jit-host-sync`` — a host-synchronizing call
  (``jax.device_get``, ``np.asarray``/``np.array``, ``.item()``,
  ``.numpy()``, ``.tolist()``, ``float()/int()/bool()`` on a non-literal)
  inside a function reachable from a ``@jax.jit`` / ``shard_map`` /
  ``to_static`` entry point.  Inside a trace these either fail on a
  tracer or, worse, silently force a device round-trip per call.
* ``trace-hygiene.device-sync`` — the same sync applied to a value the
  local dataflow proves device-resident (assigned from ``apply_op`` /
  ``jnp.*`` / ``jax.*``), or ``.item()/.numpy()/.tolist()`` on a function
  parameter: a blocking transfer in library code that runs per step (the
  ``DeviceLossList`` class of bug — one ``.item()`` per element turns a
  dispatch-ahead loop into a host-locked crawl).
* ``trace-hygiene.traced-control-flow`` — ``if``/``while`` on a traced
  parameter of a jit entry function: concretization error at best,
  silent retrace-per-branch at worst.
"""
from __future__ import annotations

import ast

from ..core import Checker, Finding
from ..module import ModuleInfo, body_nodes

R_JIT = "trace-hygiene.jit-host-sync"
R_DEV = "trace-hygiene.device-sync"
R_FLOW = "trace-hygiene.traced-control-flow"

_SYNC_METHODS = {"item", "numpy", "tolist"}
_CASTS = {"float", "int", "bool"}
_HINT_SYNC = ("keep the value on device (jnp ops / apply_op) or move the "
              "sync out of the jit-reachable path; see docs/PERF.md on "
              "per-step host syncs")
_HINT_FLOW = ("python branching concretizes a tracer; use jnp.where / "
              "lax.cond, or mark the argument static_argnums if it is "
              "genuinely a python value")


def _bare_name_in(expr, names: set[str]) -> str | None:
    """First name from `names` used bare in `expr` (not through an
    attribute like `.shape`, which is static under trace)."""
    skip: set[int] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute):
            for sub in ast.walk(node):
                skip.add(id(sub))
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in names and \
                id(node) not in skip:
            return node.id
    return None


def _is_numpy_coerce(dotted: str | None) -> bool:
    return dotted in ("numpy.asarray", "numpy.array")


def _is_device_get(dotted: str | None) -> bool:
    return bool(dotted) and (dotted == "jax.device_get" or
                             dotted.endswith(".device_get"))


def _device_producing(mod: ModuleInfo, call: ast.Call) -> bool:
    d = mod.dotted_name(call.func)
    if not d:
        return False
    if d.startswith("jax.numpy.") or d.startswith("jax.lax.") or \
            d in ("jax.device_put",):
        return True
    return d.rsplit(".", 1)[-1] == "apply_op"


class TraceHygieneChecker(Checker):
    name = "trace-hygiene"
    rules = (R_JIT, R_DEV, R_FLOW)

    # -- per-module: local dataflow (device-sync) ----------------------------
    def check_module(self, mod: ModuleInfo, project):
        out = []
        for fi in mod.functions:
            out.extend(self._device_sync_in(mod, fi))
        return out

    def _device_sync_in(self, mod: ModuleInfo, fi):
        """Flow-insensitive taint: names ever assigned from a
        device-producing expression (apply_op / jnp.* / jax.* call, or
        arithmetic/method chains over tainted names), then flag host
        syncs applied to them."""
        params = set(fi.params())
        tainted: set[str] = set()
        out = []

        def expr_tainted(e) -> bool:
            if isinstance(e, ast.Call):
                if _device_producing(mod, e):
                    return True
                # method chained off a tainted value: t.sum(), t.astype()
                if isinstance(e.func, ast.Attribute) and \
                        expr_tainted(e.func.value):
                    return True
                return False
            if isinstance(e, ast.Name):
                return e.id in tainted
            if isinstance(e, ast.BinOp):
                return expr_tainted(e.left) or expr_tainted(e.right)
            if isinstance(e, ast.UnaryOp):
                return expr_tainted(e.operand)
            if isinstance(e, (ast.Subscript, ast.Attribute)):
                return expr_tainted(e.value)
            return False

        # taint to fixpoint (chains like b = a + 1 after a = jnp.sum(x))
        changed = True
        while changed:
            changed = False
            for st in body_nodes(fi.node):
                targets = ()
                if isinstance(st, ast.Assign):
                    targets, value = st.targets, st.value
                elif isinstance(st, ast.AugAssign):
                    targets, value = (st.target,), st.value
                for t in targets:
                    if isinstance(t, ast.Name) and t.id not in tainted \
                            and expr_tainted(value):
                        tainted.add(t.id)
                        changed = True

        def flag(node, what, target):
            out.append(Finding(
                R_DEV, mod.rel, node.lineno, node.col_offset,
                symbol=fi.qualname,
                message=f"host sync: {what} on device value `{target}`",
                hint=_HINT_SYNC))

        for node in body_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS:
                base = f.value
                if isinstance(base, ast.Name) and (
                        base.id in tainted or base.id in params):
                    flag(node, f".{f.attr}()", base.id)
                elif expr_tainted(base):
                    flag(node, f".{f.attr}()", "<expr>")
            elif isinstance(f, ast.Name) and f.id in _CASTS:
                if node.args and expr_tainted(node.args[0]):
                    flag(node, f"{f.id}()", ast.unparse(node.args[0]))
            else:
                d = mod.dotted_name(f)
                if (_is_device_get(d) or _is_numpy_coerce(d)) and \
                        node.args and expr_tainted(node.args[0]):
                    flag(node, d.rsplit(".", 1)[-1] + "()",
                         ast.unparse(node.args[0]))
        return out

    # -- project-wide: jit reachability --------------------------------------
    def finalize(self, project):
        cg = project.callgraph()
        out = []
        for mod in project.modules:
            for fi in mod.functions:
                if not cg.is_reachable(fi):
                    continue
                entry = cg.entry_for(fi)
                out.extend(self._jit_syncs(mod, fi, entry,
                                           cg.entry_of.get(fi)))
        for e in cg.entries:
            out.extend(self._traced_flow(e))
        return out

    def _jit_syncs(self, mod: ModuleInfo, fi, entry: str, entry_obj=None):
        out = []
        where = (f"jit entry `{fi.qualname}`" if fi.qualname == entry else
                 f"`{fi.qualname}` (reachable from jit entry `{entry}`)")
        traced = set(entry_obj.traced_params()) if entry_obj else set()
        for node in body_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            what = None
            if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS:
                what = f".{f.attr}()"
            elif isinstance(f, ast.Name) and f.id in _CASTS:
                # only flag casts provably applied to a traced parameter
                # of the entry itself — a cast on an arbitrary local in
                # reachable code is usually python-scalar plumbing
                if node.args and traced:
                    pname = _bare_name_in(node.args[0], traced)
                    if pname:
                        what = f"{f.id}() on traced parameter `{pname}`"
            else:
                d = mod.dotted_name(f)
                if _is_device_get(d) or _is_numpy_coerce(d):
                    what = d + "()"
            if what is not None:
                out.append(Finding(
                    R_JIT, mod.rel, node.lineno, node.col_offset,
                    symbol=fi.qualname,
                    message=f"host sync {what} inside {where}",
                    hint=_HINT_SYNC))
        return out

    def _traced_flow(self, entry):
        fi = entry.func
        mod = fi.module
        traced = set(entry.traced_params())
        out = []
        for node in body_nodes(fi.node):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            name = self._traced_name_in_test(node.test, traced)
            if name:
                kind = "if" if isinstance(node, ast.If) else "while"
                out.append(Finding(
                    R_FLOW, mod.rel, node.lineno, node.col_offset,
                    symbol=fi.qualname,
                    message=(f"python `{kind}` on traced parameter "
                             f"`{name}` of jit entry `{fi.qualname}`"),
                    hint=_HINT_FLOW))
        return out

    @staticmethod
    def _traced_name_in_test(test, traced: set[str]) -> str | None:
        """First traced param used as a *value* in the test; usages inside
        isinstance/hasattr/getattr/len and `is (not) None` checks are
        python-level and exempt."""
        exempt_calls = {"isinstance", "hasattr", "getattr", "len", "type"}
        skip: set[int] = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in exempt_calls:
                for sub in ast.walk(node):
                    skip.add(id(sub))
            if isinstance(node, ast.Compare) and \
                    all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops):
                for sub in ast.walk(node):
                    skip.add(id(sub))
            if isinstance(node, ast.Attribute):
                # x.shape / x.dtype / x.ndim are static under trace
                for sub in ast.walk(node):
                    skip.add(id(sub))
        for node in ast.walk(test):
            if isinstance(node, ast.Name) and node.id in traced and \
                    id(node) not in skip:
                return node.id
        return None
