"""Ratchet baseline — freeze pre-existing findings, fail on new ones,
only ever shrink.

The baseline is a checked-in JSON multiset of finding fingerprints
(rule + path + symbol + message — no line numbers, so unrelated edits
don't invalidate it).  ``compare`` splits current findings into *new*
(not in the baseline -> gate failure) and reports *fixed* entries
(in the baseline, no longer found -> the baseline may shrink).
``update`` enforces the ratchet direction: it refuses to write a
baseline that grows unless explicitly forced (initial generation).
"""
from __future__ import annotations

import json
import os
from collections import Counter

from .core import Finding

SCHEMA = "tpu_lint.baseline.v1"


def _counter(findings) -> Counter:
    return Counter(f.fingerprint() for f in findings)


def load(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("schema") != SCHEMA:
        raise ValueError(f"{path}: not a {SCHEMA} file")
    return data


def baseline_counter(data: dict) -> Counter:
    c: Counter = Counter()
    for e in data.get("findings", []):
        c[e["fingerprint"]] += int(e.get("count", 1))
    return c


def compare(findings: list[Finding], data: dict
            ) -> tuple[list[Finding], list[str]]:
    """-> (new findings not covered by the baseline, fixed fingerprints
    present in the baseline but no longer found)."""
    allowed = baseline_counter(data)
    seen: Counter = Counter()
    new = []
    for f in sorted(findings, key=Finding.sort_key):
        fp = f.fingerprint()
        seen[fp] += 1
        if seen[fp] > allowed.get(fp, 0):
            new.append(f)
    fixed = []
    for fp, n in sorted(allowed.items()):
        if seen.get(fp, 0) < n:
            fixed.append(fp)
    return new, fixed


def render(findings: list[Finding]) -> dict:
    cur = _counter(findings)
    by_fp = {}
    for f in findings:
        by_fp.setdefault(f.fingerprint(), f)
    entries = []
    for fp in sorted(cur):
        f = by_fp[fp]
        entries.append({"fingerprint": fp, "rule": f.rule, "path": f.path,
                        "symbol": f.symbol, "message": f.message,
                        "count": cur[fp]})
    return {"schema": SCHEMA, "findings": entries}


def update(path: str, findings: list[Finding], force: bool = False) -> dict:
    """Write the baseline for the current findings.  The ratchet only
    turns one way: when `path` already exists, any fingerprint not
    already frozen is rejected (fix the code instead) unless `force`."""
    data = render(findings)
    if os.path.exists(path) and not force:
        old = baseline_counter(load(path))
        cur = _counter(findings)
        grown = sorted(fp for fp in cur if cur[fp] > old.get(fp, 0))
        if grown:
            raise ValueError(
                "baseline may only shrink; refusing to add "
                f"{len(grown)} new fingerprint(s) (first: {grown[0]!r}). "
                "Fix the new findings, suppress them with a justified "
                "'# tpu-lint: ok(rule)' comment, or pass --force for an "
                "intentional re-freeze.")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")
    return data
