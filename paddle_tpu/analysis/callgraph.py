"""Jit entry points and reachability — the shared spine of the
trace-hygiene and retrace checkers.

An **entry point** is a function that jax traces: decorated with
``@jax.jit`` (or a ``jax.jit(...)`` factory / ``functools.partial``
thereof), or passed by name to ``jax.jit`` / ``shard_map`` /
``to_static``.  For each entry we record its static argument names
(``static_argnums`` / ``static_argnames`` with literal values) — those
parameters are python values, not tracers.

**Reachability** is a BFS over resolvable calls: bare names through the
lexical scope chain (nested defs -> module defs -> from-imports into
scanned modules), ``self.method`` within the enclosing class, and
``module.func`` through the import map when the target module is in the
scanned set.  Dynamic dispatch (``opt.update``, callbacks, model calls)
is out of scope by design — the walker only claims what it can prove.
"""
from __future__ import annotations

import ast

from .module import FuncInfo, ModuleInfo, body_nodes

_JIT_FINAL = {"shard_map", "to_static", "pjit"}


def is_jit_wrapper(dotted: str | None) -> bool:
    if not dotted:
        return False
    if dotted == "jax.jit" or dotted.endswith(".jax.jit"):
        return True
    return dotted.rsplit(".", 1)[-1] in _JIT_FINAL


def _literal_static(call: ast.Call) -> tuple[set[int], set[str]]:
    """static_argnums/static_argnames when given as literals."""
    nums: set[int] = set()
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
        elif kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
    return nums, names


class Entry:
    __slots__ = ("func", "via", "static_params")

    def __init__(self, func: FuncInfo, via: str, static_params: set[str]):
        self.func = func
        self.via = via                   # what made it an entry ("jax.jit")
        self.static_params = static_params

    def traced_params(self) -> list[str]:
        return [p for p in self.func.params()
                if p not in self.static_params and p != "self"]


class CallGraph:
    def __init__(self, project):
        self.project = project
        self.entries: list[Entry] = []
        self.entry_of: dict[FuncInfo, Entry] = {}
        # FuncInfo -> the entry qualname it is reachable from (first found)
        self.reachable: dict[FuncInfo, str] = {}
        for mod in project.modules:
            self._find_entries(mod)
        self._propagate()

    # -- entry detection -----------------------------------------------------
    def _add_entry(self, func: FuncInfo, via: str, nums: set[int],
                   names: set[str]):
        if func in self.entry_of:
            return
        params = [p for p in func.params() if p != "self"]
        static = set(names)
        for i in nums:
            if 0 <= i < len(params):
                static.add(params[i])
        e = Entry(func, via, static)
        self.entries.append(e)
        self.entry_of[func] = e

    def _find_entries(self, mod: ModuleInfo):
        for fi in mod.functions:
            for dec in fi.node.decorator_list:
                target, call = dec, None
                if isinstance(dec, ast.Call):
                    target, call = dec.func, dec
                    # functools.partial(jax.jit, static_argnums=...)
                    d = mod.dotted_name(target)
                    if d and d.rsplit(".", 1)[-1] == "partial" and dec.args:
                        inner = mod.dotted_name(dec.args[0])
                        if is_jit_wrapper(inner):
                            nums, names = _literal_static(dec)
                            self._add_entry(fi, inner, nums, names)
                            continue
                d = mod.dotted_name(target)
                if is_jit_wrapper(d):
                    nums, names = (_literal_static(call) if call is not None
                                   else (set(), set()))
                    self._add_entry(fi, d, nums, names)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = mod.dotted_name(node.func)
            if not is_jit_wrapper(d) or not node.args:
                continue
            arg0 = node.args[0]
            if not isinstance(arg0, ast.Name):
                continue
            enclosing = mod.enclosing_function(node)
            target = self._resolve_bare(mod, enclosing, arg0.id)
            if isinstance(target, FuncInfo):
                nums, names = _literal_static(node)
                self._add_entry(target, d, nums, names)

    # -- call resolution -----------------------------------------------------
    def _resolve_bare(self, mod: ModuleInfo, scope: FuncInfo | None,
                      name: str):
        cur = scope
        while cur is not None:
            if name in cur.local_defs:
                return cur.local_defs[name]
            cur = cur.parent
        if name in mod.top_defs:
            return mod.top_defs[name]
        dotted = mod.imports.get(name)
        if dotted:
            return self._resolve_dotted(dotted)
        return None

    def _resolve_dotted(self, dotted: str):
        """paddle_tpu.core.op.apply_op -> FuncInfo when the owning module
        is in the scanned set."""
        if "." not in dotted:
            return None
        mod_name, func_name = dotted.rsplit(".", 1)
        target_mod = self.project.by_dotted.get(mod_name)
        if target_mod is not None:
            return target_mod.top_defs.get(func_name)
        return None

    def resolve_call(self, mod: ModuleInfo, scope: FuncInfo | None,
                     call: ast.Call):
        """FuncInfo for a call when statically resolvable, else None."""
        func = call.func
        if isinstance(func, ast.Name):
            return self._resolve_bare(mod, scope, func.id)
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and func.value.id == "self" \
                    and scope is not None and scope.cls is not None:
                return mod.methods.get(scope.cls.name, {}).get(func.attr)
            d = mod.dotted_name(func)
            if d:
                return self._resolve_dotted(d)
        return None

    # -- reachability --------------------------------------------------------
    def _propagate(self):
        work = []
        for e in self.entries:
            if e.func not in self.reachable:
                self.reachable[e.func] = e.func.qualname
                work.append(e.func)
        while work:
            fi = work.pop()
            via = self.reachable[fi]
            for node in body_nodes(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                target = self.resolve_call(fi.module, fi, node)
                if isinstance(target, FuncInfo) and \
                        target not in self.reachable:
                    self.reachable[target] = via
                    work.append(target)

    def is_reachable(self, fi: FuncInfo) -> bool:
        return fi in self.reachable

    def entry_for(self, fi: FuncInfo) -> str | None:
        return self.reachable.get(fi)
